"""Isolate Pallas kernel HBM throughput: trivial copy vs the fused-BN
component kernels, over block sizes. All timings are chained-k-loop
in-process A/B (see bn_bwd_probe.py)."""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, ".")
from horovod_tpu.ops import fused_bn  # noqa: E402

M2, C2 = 802816, 256
K = 20
SIZE_MB = M2 * C2 * 2 / 1e6


def loop(step):
    @jax.jit
    def run(x, g):
        def body(_, carry):
            x, g = carry
            return step(x, g), x
        x, g = jax.lax.fori_loop(0, K, body, (x, g))
        return x
    return run


def timed(fn, args, reps=3):
    out = fn(*args)
    _ = float(jnp.sum(out[:8, :8].astype(jnp.float32)))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        _ = float(jnp.sum(out[:8, :8].astype(jnp.float32)))
        ts.append((time.perf_counter() - t0) / K)
    return float(np.median(ts))


def copy_kernel(x_ref, y_ref):
    y_ref[:] = x_ref[:]


def addone_kernel(x_ref, y_ref):
    y_ref[:] = x_ref[:] + jnp.bfloat16(1.0)


def stats_like_kernel(x_ref, y_ref):
    # reduce-only: read block, accumulate channel sums (writes tiny)
    @pl.when(pl.program_id(0) == 0)
    def _():
        y_ref[:] = jnp.zeros_like(y_ref)
    xf = x_ref[:].astype(jnp.float32)
    y_ref[:] += jnp.sum(xf, axis=0, keepdims=True)
    y_ref[:] += jnp.sum(xf * xf, axis=0, keepdims=True)


def make_pallas_map(kernel, bm, out_c=None, out_dtype=jnp.bfloat16):
    grid = (M2 // bm,)
    if out_c is None:  # elementwise map
        out_specs = pl.BlockSpec((bm, C2), lambda i: (i, 0))
        out_shape = jax.ShapeDtypeStruct((M2, C2), out_dtype)
    else:
        out_specs = pl.BlockSpec((1, C2), lambda i: (0, 0))
        out_shape = jax.ShapeDtypeStruct((1, C2), jnp.float32)
    f = pl.pallas_call(
        kernel, grid=grid,
        in_specs=[pl.BlockSpec((bm, C2), lambda i: (i, 0))],
        out_specs=out_specs, out_shape=out_shape)

    def step(x, g):
        out = f(x)
        if out_c is not None:
            # feed something x-shaped back for the chain
            return x + out[0, :C2].astype(x.dtype)
        return out
    return step


def main():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (M2, C2), jnp.bfloat16)
    g = jax.random.normal(key, (M2, C2), jnp.bfloat16)
    print("device:", jax.devices()[0].device_kind, flush=True)
    base = SIZE_MB * 1e6 / 819e9 * 1e3
    print(f"tensor: {SIZE_MB:.0f} MB; 1 pass = {base:.2f} ms", flush=True)

    def xla_add(x, g):
        return x + jnp.bfloat16(1.0)

    progs = {"xla y=x+1 (2 passes)": loop(xla_add)}
    for bm in (256, 512, 1024, 2048):
        progs[f"pallas copy bm={bm} (2 passes)"] = loop(
            make_pallas_map(copy_kernel, bm))
    for bm in (512, 1024, 2048):
        progs[f"pallas stats bm={bm} (1 pass)"] = loop(
            make_pallas_map(stats_like_kernel, bm, out_c=C2))

    for rnd in range(2):
        for name, prog in progs.items():
            t = timed(prog, (x, g))
            print(f"[{rnd}] {name}: {t*1e3:.2f} ms "
                  f"(~{t*1e3/base:.1f} passes)", flush=True)


if __name__ == "__main__":
    main()
