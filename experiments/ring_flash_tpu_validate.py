"""Compiled-TPU validation of ring_flash_attention (fwd + bwd).

Multi-chip hardware isn't reachable from this box, but the full ring
code path — lax.scan over ring steps, the branch switch, the streaming
logaddexp merge, the custom VJP with traveling dk/dv accumulators, and
the COMPILED Mosaic flash kernels (interpret=False) — runs on one real
chip under ``jax.vmap`` with an ``axis_name``: vmap binds the axis so
``ppermute``/``axis_index`` execute sequentially on-device with
identical semantics to the multi-chip mesh. The only thing this does
not cover is the physical ICI transfer, which is XLA's, not ours.

Backward uses jax.vjp *inside* the vmap lane with the per-lane
cotangent (2*out for a sum-of-squares loss) — grad-of-psum under vmap
hits JAX's psum-transpose convention and is NOT the multi-chip
semantics, so it is deliberately avoided here.

Prints one JSON line; tee to ring_flash_tpu.log. Referenced from
docs/parallelism.md (ring-flash auto-select validation).
"""
import json
import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from horovod_tpu.parallel.ring_attention import (  # noqa: E402
    ring_flash_attention, full_attention)

B, S, H, D, N = 2, 4096, 8, 64, 4   # 1024-token shards: the auto-select
BLOCK = None                        # regime (>=1024 attended tokens)
DTYPE = jnp.bfloat16


def main():
    backend = jax.default_backend()
    interpret = backend != "tpu"
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), DTYPE)
               for kk in jax.random.split(key, 3))

    def shard(x):
        return x.reshape(B, N, S // N, H, D).transpose(1, 0, 2, 3, 4)

    def unshard(y):
        return y.transpose(1, 0, 2, 3, 4).reshape(B, S, H, D)

    def ring(qs, ks, vs):
        return ring_flash_attention(qs, ks, vs, "sp", True, None,
                                    BLOCK, interpret)

    @jax.jit
    def fwd(qs, ks, vs):
        return jax.vmap(ring, axis_name="sp")(qs, ks, vs)

    @jax.jit
    def bwd(qs, ks, vs):
        def local(qs, ks, vs):
            out, vjp = jax.vjp(ring, qs, ks, vs)
            return vjp((2.0 * out.astype(jnp.float32)).astype(qs.dtype))
        return jax.vmap(local, axis_name="sp")(qs, ks, vs)

    t0 = time.time()
    out = unshard(jax.block_until_ready(fwd(shard(q), shard(k), shard(v))))
    dq, dk, dv = (unshard(g) for g in
                  jax.block_until_ready(bwd(shard(q), shard(k), shard(v))))
    elapsed = time.time() - t0

    ref = full_attention(q, k, v, causal=True)

    def ref_loss(q, k, v):
        return jnp.sum(
            full_attention(q, k, v, causal=True).astype(jnp.float32) ** 2)

    rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)

    def err(a, b):
        sc = float(jnp.max(jnp.abs(b.astype(jnp.float32)))) or 1.0
        return float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                     - b.astype(jnp.float32)))) / sc

    errs = {"fwd": err(out, ref), "dq": err(dq, rq),
            "dk": err(dk, rk), "dv": err(dv, rv)}
    # bf16 operands: ~8 mantissa bits => relative tolerance ~2%.
    ok = all(e < 0.05 for e in errs.values())
    print(json.dumps({
        "metric": "ring_flash_compiled_validation",
        "value": max(errs.values()),
        "unit": "max relative error (vs full attention, bf16)",
        "ok": ok, "errors": {k2: round(e, 5) for k2, e in errs.items()},
        "backend": backend, "interpret": interpret,
        "shape": [B, S, H, D], "ring_shards": N,
        "elapsed_s": round(elapsed, 1),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
