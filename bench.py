#!/usr/bin/env python
"""Synthetic ResNet-50 benchmark — the TPU-native equivalent of
examples/tensorflow_synthetic_benchmark.py (the reference's in-tree
benchmark driver, :88-107): ResNet-50 on synthetic ImageNet-shaped data,
warmup batches then timed iterations, reporting img/sec — plus MFU
(model FLOPs utilization) and an optional weak-scaling sweep, the two
numbers BASELINE.md actually cares about (docs/benchmarks.md:5-38).

Method: the reference's window STRUCTURE (10 timed windows; mean +/-
1.96 sigma also reported) with two measured corrections — 40 batches
per window (each host call through the axon tunnel carries ~90 ms of
fixed RPC overhead that is plumbing, not chip time; see the
NUM_BATCHES_PER_ITER comment) and a median headline (one stalled
tunnel window out of 10 drags a mean by tens of percent; the raw
per-window values are in the JSON so the choice is auditable). At
least 3 warmup calls reach the jit donation/sharding fixpoint. Trains
through the framework path: mesh over all available devices, batch
sharded over 'dp', DistributedOptimizer.

MFU methodology: FLOPs per optimizer step are taken from XLA's own cost
analysis of the compiled single-step program (no hand-counted model
constants), divided by measured step time and the chip's peak bf16
FLOP/s looked up from ``device_kind``. Peak numbers are the published
per-chip bf16 figures (v2 45, v3 123, v4 275, v5e 197, v5p 459,
v6e 918 TFLOP/s).

Weak scaling (--scaling N1,N2,... or HVD_BENCH_SCALING): for each N, a
runner-launched N-process job (1 virtual CPU device per process — the
same launch path a real multi-host pod uses, SURVEY.md §4) trains the
same model; efficiency(N) = throughput(N) / (N * throughput(1)), the
shape of the reference's 90%-at-512-GPUs headline (docs/benchmarks.md:
5-6). CPU-mesh numbers measure the framework's collective/control-plane
overhead, not ICI hardware.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/sec/chip", "vs_baseline": N,
   "mfu": ..., "tflops_per_chip": ..., "peak_tflops": ...[,
   "weak_scaling": {...}]}
Baseline: the reference's sample run reports "total images/sec: 1656.82"
on 16 Pascal GPUs (docs/benchmarks.md:22-38) = 103.55 img/sec/GPU.
"""

import argparse
import json
import os
import time
from functools import partial

import numpy as np

BASELINE_IMG_SEC_PER_CHIP = 1656.82 / 16  # docs/benchmarks.md:22-38

BATCH_PER_CHIP = int(os.environ.get("HVD_BENCH_BATCH", 256))
IMAGE_SIZE = int(os.environ.get("HVD_BENCH_IMAGE", 224))
WARMUP_BATCHES = int(os.environ.get("HVD_BENCH_WARMUP", 10))  # ref :88-92
NUM_ITERS = int(os.environ.get("HVD_BENCH_ITERS", 10))
# 40 batches per timed window, up from the reference's 10: each host
# call through the axon device tunnel carries ~90 ms of fixed RPC +
# sync-readback overhead (measured round 4: identical step program,
# 110.7 ms/step at k=10 vs 102.0 at k=40), which is tunnel plumbing,
# not chip time — the number BASELINE.md compares is chip throughput,
# so the window must amortize it. The reference's 10-iteration window
# STRUCTURE (mean/median over 10 timed windows) is unchanged.
NUM_BATCHES_PER_ITER = int(os.environ.get("HVD_BENCH_BATCHES", 40))

# Published peak bf16 TFLOP/s per chip, keyed by substrings of
# jax.Device.device_kind. (v5 lite == v5e; v6 lite == v6e/Trillium.)
PEAK_TFLOPS_BY_KIND = [
    ("v6 lite", 918.0), ("v6e", 918.0),
    ("v5 lite", 197.0), ("v5litepod", 197.0), ("v5e", 197.0),
    ("v5p", 459.0), ("v5", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]


def peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in PEAK_TFLOPS_BY_KIND:
        if key in kind:
            return peak
    return 0.0  # unknown (CPU run) — mfu reported as 0/None


# Windows whose wall time exceeds the median by this factor are tunnel
# stalls, not chip behavior (VERDICT r5 weak #3: one 16.7 s window in a
# ~6.6 s-median run blew ci95 from ±16 to ±1118). Overridable for
# environments with different stall shapes.
STALL_FACTOR = float(os.environ.get("HVD_BENCH_STALL_FACTOR", 1.5))


def annotate_stalled_windows(window_s, stall_factor=None):
    """Detect wall-time outlier windows against the run's own median.

    Returns ``(stalled_indices, ok_indices)``. The raw windows stay in
    the JSON untouched — this only *annotates* them so round-over-round
    ci95 comparisons can exclude stalls instead of reading a tunnel
    hiccup as a throughput regression. If every window would be flagged
    (degenerate tiny medians), nothing is: a uniformly slow run is slow,
    not stalled."""
    factor = STALL_FACTOR if stall_factor is None else stall_factor
    if not window_s:
        return [], []
    med = float(np.median(window_s))
    stalled = [i for i, w in enumerate(window_s) if w > factor * med]
    if len(stalled) == len(window_s):
        stalled = []
    ok = [i for i in range(len(window_s)) if i not in set(stalled)]
    return stalled, ok


def build_step(model, opt):
    """One jitted k-step training program (state donated; the k optimizer
    steps run inside a single lax.fori_loop so host dispatch latency never
    sits between device steps)."""
    import jax
    import jax.numpy as jnp
    import optax

    @partial(jax.jit, donate_argnums=(0, 1, 2), static_argnums=(5,))
    def train_k(params, batch_stats, opt_state, images, labels, k):
        def body(_, carry):
            params, batch_stats, opt_state = carry

            def loss_fn(p):
                logits, new_state = model.apply(
                    {"params": p, "batch_stats": batch_stats}, images,
                    train=True, mutable=["batch_stats"])
                loss = optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels).mean()
                return loss, new_state["batch_stats"]

            (_, new_bs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, new_opt = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_bs, new_opt

        return jax.lax.fori_loop(0, k, body,
                                 (params, batch_stats, opt_state))

    return train_k


def run_chip_bench():
    """Single-process benchmark over all local devices (the driver's
    real-TPU run). Returns the result dict."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models import ResNet50

    hvd.init()
    n = hvd.size()
    mesh = hvd.mesh()
    batch = BATCH_PER_CHIP * n

    model = ResNet50(num_classes=1000)
    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(rng, (batch, IMAGE_SIZE, IMAGE_SIZE, 3),
                               jnp.float32)
    labels = jax.random.randint(rng, (batch,), 0, 1000)

    variables = model.init(rng, images[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    # Framework path: broadcast initial state from rank 0, then wrap the
    # optimizer (grads are averaged over the mesh inside the jitted step).
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt = hvd.DistributedGradientTransformation(
        optax.sgd(0.01 * n, momentum=0.9))
    opt_state = opt.init(params)

    if n > 1:
        images = jax.device_put(images, NamedSharding(mesh, P("dp")))
        labels = jax.device_put(labels, NamedSharding(mesh, P("dp")))

    train_k = build_step(model, opt)

    # FLOPs per optimizer step from XLA's cost analysis of a k=1
    # program. This is a second, dedicated compile on purpose: cost
    # analysis of a k>1 executable reports a NON-linear flop total
    # (measured: k=10 gives ~1.5x the k=1 figure, not 10x — loop
    # canonicalization), so the k=1 program is the only unambiguous
    # per-step basis. HVD_BENCH_SKIP_MFU=1 skips it (CI smoke, where
    # the duplicate compile is the dominant cost and MFU is meaningless
    # on CPU anyway).
    flops_per_step = 0.0
    if os.environ.get("HVD_BENCH_SKIP_MFU") != "1":
        try:
            cost = train_k.lower(params, batch_stats, opt_state, images,
                                 labels, 1).compile().cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            flops_per_step = float(cost.get("flops", 0.0))
        except Exception:
            pass

    def run_batches(k):
        nonlocal params, batch_stats, opt_state
        params, batch_stats, opt_state = train_k(
            params, batch_stats, opt_state, images, labels, k)
        # Block with a device-to-host read of the updated parameters: the
        # float() cannot be reported "ready" early by any runtime
        # (block_until_ready alone is unreliable through device tunnels).
        return float(jnp.sum(jax.tree_util.tree_leaves(params)[0]))

    # Warmup (compile + stabilize), reference :88-92. Warmup calls use
    # the SAME static k as the timed iterations: a different k would
    # compile a different executable, pushing the timed k's compile into
    # the first measured window — so WARMUP_BATCHES rounds up to whole
    # iterations, with a floor of 3 calls: the jit signature reaches its
    # donation/committed-sharding fixpoint only after ~3 calls, and a
    # recompile inside window 0 shows up as a 6x wall-time outlier
    # (visible in windows_wall_s of any run that skips this).
    for _ in range(max(-(-WARMUP_BATCHES // NUM_BATCHES_PER_ITER), 3)):
        run_batches(NUM_BATCHES_PER_ITER)

    # Timed iterations (reference :94-101). Raw per-window times are
    # recorded in the JSON (VERDICT r3 #7) so a future reader can tell
    # a drifting tunnel from a real regression.
    img_secs = []
    window_s = []
    for _ in range(NUM_ITERS):
        t0 = time.perf_counter()
        run_batches(NUM_BATCHES_PER_ITER)
        dt = time.perf_counter() - t0
        window_s.append(round(dt, 4))
        img_secs.append(batch * NUM_BATCHES_PER_ITER / dt)

    # Median over the iteration windows as the headline (one tunnel
    # stall out of 10 windows drags a mean by tens of percent — measured
    # ci95 of ±63% with a single stalled window); the reference's
    # mean ± 1.96σ (tensorflow_synthetic_benchmark.py:88-107) is still
    # reported so round-over-round deltas stay interpretable on its
    # convention too.
    per_chip = float(np.median(img_secs)) / n
    mean = float(np.mean(img_secs)) / n
    ci95 = float(1.96 * np.std(img_secs)) / n
    # Stall annotation (VERDICT r5 weak #3): keep every raw window, but
    # flag wall-time outliers and report a trimmed mean/CI over the
    # clean windows so cross-round ci95 comparisons don't read one
    # stalled tunnel window as a regression. The median headline is
    # already stall-robust and unchanged.
    stalled_idx, ok_idx = annotate_stalled_windows(window_s)
    ok_rates = [img_secs[i] for i in ok_idx] or img_secs
    trimmed_mean = float(np.mean(ok_rates)) / n
    trimmed_ci95 = float(1.96 * np.std(ok_rates)) / n
    peak = peak_tflops(jax.devices()[0])
    # MFU on the same basis as the reported rate: sustained FLOP/s =
    # (reported img/sec/chip) x (FLOPs per image), so the two headline
    # numbers cannot disagree about what was measured. cost_analysis
    # reports the PER-DEVICE partitioned executable's flops, so divide
    # by the per-device batch, not the global one.
    flops_per_img = flops_per_step / (batch / n) if batch else 0.0
    tflops = per_chip * flops_per_img / 1e12
    mfu = tflops / peak if peak else 0.0
    return {
        "metric": "resnet50_synthetic_img_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_SEC_PER_CHIP, 3),
        "mean": round(mean, 2),
        "ci95": round(ci95, 2),
        "iters": NUM_ITERS,
        "batches_per_iter": NUM_BATCHES_PER_ITER,
        "windows_img_sec_per_chip": [round(v / n, 2) for v in img_secs],
        "windows_wall_s": window_s,
        "stalled_windows": stalled_idx,
        "stall_factor": STALL_FACTOR,
        "trimmed_mean": round(trimmed_mean, 2),
        "trimmed_ci95": round(trimmed_ci95, 2),
        "mfu": round(mfu, 4),
        "tflops_per_chip": round(tflops, 1),
        "peak_tflops": peak,
        "batch_per_chip": BATCH_PER_CHIP,
    }


def _scaling_worker():
    """Per-process weak-scaling workload: a small bottleneck ResNet so the
    CPU mesh turns steps in seconds, with full-size-realistic gradient
    traffic through the same DistributedOptimizer/allreduce path.

    HVD_BENCH_SCALE_MODEL=vgg swaps in a VGG-shaped proxy — conv stack
    plus a deliberately fat fc head — preserving VGG-16's defining
    ratio (the reference's worst-scaling family, 68% at 512 GPUs,
    docs/benchmarks.md:5-6): far more gradient bytes per unit compute
    than the ResNet proxy, i.e. the tensor-fusion stress case."""
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models.resnet import ResNet

    hvd.init()
    n = hvd.size()
    batch_per = int(os.environ.get("HVD_BENCH_SCALE_BATCH", 8))
    image = int(os.environ.get("HVD_BENCH_SCALE_IMAGE", 32))
    steps = int(os.environ.get("HVD_BENCH_SCALE_STEPS", 4))

    if os.environ.get("HVD_BENCH_SCALE_MODEL") == "vgg":
        import flax.linen as nn

        class _VGGProxy(nn.Module):
            @nn.compact
            def __call__(self, x, train=True):
                for ch in (32, 64):
                    x = nn.relu(nn.Conv(ch, (3, 3))(x))
                    x = nn.max_pool(x, (2, 2), strides=(2, 2))
                x = x.reshape(x.shape[0], -1)
                x = nn.relu(nn.Dense(2048)(x))   # the VGG fc mass:
                x = nn.relu(nn.Dense(2048)(x))   # ~17M params vs ~0.1M
                return nn.Dense(100)(x)          # of conv compute

        class _NoBN:
            """Match the ResNet worker's (logits, batch_stats) apply
            contract with an empty-stats model."""
            def __init__(self, m):
                self._m = m

            def init(self, rng, x, train=True):
                return {"params": self._m.init(rng, x)["params"],
                        "batch_stats": {}}

            def apply(self, variables, x, train=True, mutable=()):
                out = self._m.apply({"params": variables["params"]}, x)
                return out, {"batch_stats": {}}

        model = _NoBN(_VGGProxy())
    else:
        model = ResNet(stage_sizes=[1, 1, 1, 1], num_classes=100,
                       dtype=jnp.float32)
    rng = jax.random.PRNGKey(hvd.process_rank())
    images = jax.random.normal(rng, (batch_per, image, image, 3),
                               jnp.float32)
    labels = jax.random.randint(rng, (batch_per,), 0, 100)

    variables = model.init(jax.random.PRNGKey(0), images[:2], train=True)
    params, bs = variables["params"], variables["batch_stats"]
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt = optax.sgd(0.01)
    opt_state = opt.init(params)

    def loss_fn(p, bs):
        logits, new_state = model.apply(
            {"params": p, "batch_stats": bs}, images,
            train=True, mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, new_state["batch_stats"]

    grad_fn = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))

    def step(params, bs, opt_state, i):
        (_, bs), grads = grad_fn(params, bs)
        # Eager cross-process gradient averaging — the multi-host
        # DistributedOptimizer hook path (fusion + control plane live).
        grads = hvd.allreduce_gradients(grads, name_prefix=f"ws{i}")
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, bs, opt_state

    # Warmup (compile both programs + prime the engine). THREE steps, not
    # one: the first step's outputs are committed engine/device arrays
    # while the init pytree is uncommitted, so jit sees a different
    # argument signature for ~2 steps before the executable set reaches
    # its fixpoint — a single warmup left a full recompile (measured
    # ~7 s on the CPU mesh) inside the timed window.
    for w in range(3):
        params, bs, opt_state = step(params, bs, opt_state, f"w{w}")
    jax.block_until_ready(params)
    # Two timed windows of `steps` each, median window throughput
    # (reference method: mean over iteration windows,
    # tensorflow_synthetic_benchmark.py:94-101). Windows, not per-step
    # sync: blocking every step would forbid the step pipelining real
    # training has; the median across windows still rejects a
    # descheduling stall on the shared CI host.
    import numpy as _np
    rates = []
    for w in range(2):
        t0 = time.perf_counter()
        for i in range(steps):
            params, bs, opt_state = step(params, bs, opt_state,
                                         f"{w}.{i}")
        jax.block_until_ready(params)
        rates.append(batch_per * steps * n / (time.perf_counter() - t0))
    return float(_np.median(rates))  # global img/sec


def run_weak_scaling(sizes):
    """Launch an N-process job per N and print the BASELINE.md-shaped
    table.

    Two efficiency columns:
      - ``efficiency`` = thr(N) / (N * thr(1)) — the reference's headline
        shape (docs/benchmarks.md:5-6), meaningful when every process has
        its own chip.
      - ``capacity_adjusted`` = thr(N) / (min(N, cores) * thr(1)) — on a
        CI host with fewer cores than processes, compute capacity does
        not grow with N, so the perfect-framework ceiling is
        min(N, cores) * thr(1); this column isolates the framework's
        collective/control-plane overhead from plain CPU contention.
    """
    from horovod_tpu.runner.api import run as hvd_run

    env = dict(SCALING_WORKER_ENV)
    cores = os.cpu_count() or 1
    if 1 not in sizes:
        # Efficiency is defined against thr(1); measure it rather than
        # fabricating a perfect-scaling baseline from the smallest N.
        sizes = [1] + list(sizes)
    # Efficiency is a RATIO of two jobs, and absolute throughput on a
    # shared host drifts between runs minutes apart — measuring all of
    # thr(1) and then all of thr(N) bakes that drift into every ratio.
    # So rounds INTERLEAVE the sizes ([1, N1, N2, ..] per round), each
    # round's ratios use ITS OWN thr(1), and the reported number is the
    # median ratio across rounds (the in-process A/B discipline; the
    # reference's mean-over-iterations, synthetic_benchmark.py:94-101,
    # assumes a dedicated machine this host is not).
    repeats = int(os.environ.get("HVD_BENCH_SCALE_REPEATS", 3))
    rounds = []
    for _ in range(max(1, repeats)):
        rnd = {}
        for n in sizes:
            out = hvd_run(_scaling_worker, np=n, extra_env=dict(env),
                          start_timeout=600)
            rnd[n] = float(np.median(out))
        rounds.append(rnd)
    table = {}
    for n in sizes:
        effs = [r[n] / (n * r[1]) for r in rounds if r[1]]
        caps = [r[n] / (min(n, cores) * r[1]) for r in rounds if r[1]]
        table[str(n)] = {
            "img_sec": round(float(np.median([r[n] for r in rounds])), 1),
            "efficiency": round(float(np.median(effs)), 3),
            "capacity_adjusted": round(float(np.median(caps)), 3),
            "capacity_adjusted_runs": [round(c, 3) for c in caps],
        }
    table["_host_cores"] = cores
    return table


# Worker launch env shared by every scaling-path job (weak scaling and
# the autotune A/B): plain CPU, one device per process — the same
# launch shape a real multi-host pod uses.
SCALING_WORKER_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


def run_autotune_ab():
    """Certify the autotuner on the REAL training workload (VERDICT r3
    #4), not only on engine microbenches: interleaved rounds of the
    weak-scaling ResNet job (eager allreduce_gradients through the full
    engine/control-plane stack) with HOROVOD_AUTOTUNE=1 vs default
    knobs, per-round tuned/default ratio, median across rounds (the
    in-process-A/B discipline adapted to read-once engine knobs — the
    knob set forces a fresh process per arm, so the interleaving is
    between adjacent jobs rather than within one)."""
    from horovod_tpu.runner.api import run as hvd_run

    env_base = dict(SCALING_WORKER_ENV)
    # enough steps for the BO to sample several cycles and freeze
    env_base["HVD_BENCH_SCALE_STEPS"] = os.environ.get(
        "HVD_BENCH_SCALE_STEPS", "8")
    nproc = int(os.environ.get("HVD_BENCH_AUTOTUNE_NP", 2))
    repeats = int(os.environ.get("HVD_BENCH_AUTOTUNE_REPEATS", 3))
    tuned_r, default_r, ratios = [], [], []
    for _ in range(max(1, repeats)):
        env_t = dict(env_base)
        env_t["HOROVOD_AUTOTUNE"] = "1"
        tuned = float(np.median(hvd_run(
            _scaling_worker, np=nproc, extra_env=env_t,
            start_timeout=600)))
        default = float(np.median(hvd_run(
            _scaling_worker, np=nproc, extra_env=dict(env_base),
            start_timeout=600)))
        tuned_r.append(tuned)
        default_r.append(default)
        ratios.append(tuned / default if default else 0.0)
    return {
        "metric": "autotune_real_workload_ratio",
        "value": round(float(np.median(ratios)), 3),
        "unit": "tuned/default throughput",
        "np": nproc,
        "tuned_img_sec": round(float(np.median(tuned_r)), 1),
        "default_img_sec": round(float(np.median(default_r)), 1),
        "rounds": [round(r, 3) for r in ratios],
    }


def run_probes():
    """Re-derive the environment-calibrated roofline inputs on THIS
    hardware (VERDICT r4 weak #3): effective HBM bandwidth and the
    BN-backward pass accounting behind docs/benchmarks.md's ~2500 img/s
    ceiling were measured on a shared-tunnel bench box (~570 GB/s
    effective, ~90-100 ms per host call); on direct-attached metal they
    may differ and the ceiling claim must be re-validated from these
    numbers, not quoted."""
    import subprocess
    import sys
    here = os.path.dirname(os.path.abspath(__file__))
    for script in ("hbm_probe.py", "bn_bwd_probe.py"):
        path = os.path.join(here, "experiments", script)
        print(f"=== {script} (see docs/benchmarks.md 'Revised ceiling' "
              "for how to read it) ===", flush=True)
        subprocess.run([sys.executable, path], check=False)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--np", type=int, default=0, metavar="N",
                    help="run ONLY the weak-scaling job at N processes")
    ap.add_argument("--probes", action="store_true",
                    help="re-run the roofline calibration probes (HBM "
                         "bandwidth, BN-bwd passes) on this hardware")
    ap.add_argument("--autotune-ab", action="store_true",
                    help="run ONLY the autotune-vs-default A/B on the "
                         "real scaling workload")
    ap.add_argument("--scaling", type=str, default=os.environ.get(
        "HVD_BENCH_SCALING", ""), metavar="N1,N2,...",
        help="weak-scaling sweep process counts (e.g. 1,2,4,8)")
    ap.add_argument("--scaling-only", action="store_true",
                    help="skip the single-chip bench")
    args = ap.parse_args()

    if args.probes:
        run_probes()
        return

    if args.autotune_ab:
        print(json.dumps(run_autotune_ab()))
        return

    if args.np:
        sizes = [args.np] if args.np == 1 else [1, args.np]
        table = run_weak_scaling(sizes)
        # Headline = capacity-adjusted (the framework-overhead number a
        # shared CI host can honestly produce; on a real pod with a chip
        # per process the two columns coincide).
        # Same normalized check the worker uses — any value other than
        # exactly "vgg" runs (and must be labeled as) the ResNet proxy.
        family = ("vgg" if os.environ.get("HVD_BENCH_SCALE_MODEL") == "vgg"
                  else "resnet")
        print(json.dumps({
            "metric": f"{family}_weak_scaling",
            "value": table[str(args.np)]["capacity_adjusted"],
            "unit": "efficiency",
            "vs_baseline": round(
                table[str(args.np)]["capacity_adjusted"] / 0.90, 3),
            "weak_scaling": table,
        }))
        return

    if args.scaling_only and not args.scaling:
        ap.error("--scaling-only requires --scaling (or HVD_BENCH_SCALING)")

    result = None
    if not args.scaling_only:
        result = run_chip_bench()

    if args.scaling:
        sizes = sorted({int(s) for s in args.scaling.split(",") if s})
        table = run_weak_scaling(sizes)
        if result is None:
            top = str(max(sizes))
            result = {
                "metric": "resnet_weak_scaling",
                "value": table[top]["capacity_adjusted"],
                "unit": "efficiency",
                # reference headline: 90% scaling efficiency
                # (docs/benchmarks.md:5-6)
                "vs_baseline": round(
                    table[top]["capacity_adjusted"] / 0.90, 3),
            }
        result["weak_scaling"] = table

    print(json.dumps(result))


if __name__ == "__main__":
    main()
