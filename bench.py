#!/usr/bin/env python
"""Synthetic ResNet-50 benchmark — the TPU-native equivalent of
examples/tensorflow_synthetic_benchmark.py (the reference's in-tree
benchmark driver, :88-107): ResNet-50 on synthetic ImageNet-shaped data,
warmup batches then timed iterations, reporting img/sec.

Method parity: 10 warmup batches; 10 iterations x 10 batches each; the
reported number is the mean. Trains through the framework path: mesh over
all available devices, batch sharded over 'dp', DistributedOptimizer.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/sec/chip", "vs_baseline": N}
Baseline: the reference's sample run reports "total images/sec: 1656.82"
on 16 Pascal GPUs (docs/benchmarks.md:22-38) = 103.55 img/sec/GPU.
"""

import json
import os
import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import optax

import horovod_tpu as hvd
from horovod_tpu.models import ResNet50

BASELINE_IMG_SEC_PER_CHIP = 1656.82 / 16  # docs/benchmarks.md:22-38

BATCH_PER_CHIP = int(os.environ.get("HVD_BENCH_BATCH", 64))  # ref --batch-size
IMAGE_SIZE = int(os.environ.get("HVD_BENCH_IMAGE", 224))
WARMUP_BATCHES = int(os.environ.get("HVD_BENCH_WARMUP", 10))  # ref :88-92
NUM_ITERS = int(os.environ.get("HVD_BENCH_ITERS", 10))
NUM_BATCHES_PER_ITER = int(os.environ.get("HVD_BENCH_BATCHES", 10))


def main():
    hvd.init()
    n = hvd.size()
    mesh = hvd.mesh()
    batch = BATCH_PER_CHIP * n

    model = ResNet50(num_classes=1000)
    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(rng, (batch, IMAGE_SIZE, IMAGE_SIZE, 3),
                               jnp.float32)
    labels = jax.random.randint(rng, (batch,), 0, 1000)

    variables = model.init(rng, images[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    # Framework path: broadcast initial state from rank 0, then wrap the
    # optimizer (grads are averaged over the mesh inside the jitted step).
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt = hvd.DistributedGradientTransformation(
        optax.sgd(0.01 * n, momentum=0.9))
    opt_state = opt.init(params)

    if n > 1:
        images = jax.device_put(images, NamedSharding(mesh, P("dp")))
        labels = jax.device_put(labels, NamedSharding(mesh, P("dp")))

    # Two dispatch-efficiency levers, both legitimate training semantics:
    # 1. donate params/batch-stats/opt-state so XLA updates ~200 MB of
    #    state in place instead of double-buffering it in HBM;
    # 2. run the k optimizer steps of one timed iteration inside a single
    #    jitted lax.fori_loop — one dispatch per iteration instead of k,
    #    so host/dispatch latency does not sit between device steps.
    @partial(jax.jit, donate_argnums=(0, 1, 2), static_argnums=(5,))
    def train_k(params, batch_stats, opt_state, images, labels, k):
        def body(_, carry):
            params, batch_stats, opt_state = carry

            def loss_fn(p):
                logits, new_state = model.apply(
                    {"params": p, "batch_stats": batch_stats}, images,
                    train=True, mutable=["batch_stats"])
                loss = optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels).mean()
                return loss, new_state["batch_stats"]

            (_, new_bs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, new_opt = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_bs, new_opt

        return jax.lax.fori_loop(0, k, body,
                                 (params, batch_stats, opt_state))

    def run_batches(k):
        nonlocal params, batch_stats, opt_state
        params, batch_stats, opt_state = train_k(
            params, batch_stats, opt_state, images, labels, k)
        # Block with a device-to-host read of the updated parameters: the
        # float() cannot be reported "ready" early by any runtime
        # (block_until_ready alone is unreliable through device tunnels).
        return float(jnp.sum(jax.tree_util.tree_leaves(params)[0]))

    # Warmup (compile + stabilize), reference :88-92. Warmup calls use
    # the SAME static k as the timed iterations: a different k would
    # compile a different executable, pushing the timed k's compile into
    # the first measured window — so WARMUP_BATCHES rounds up to whole
    # iterations.
    for _ in range(-(-WARMUP_BATCHES // NUM_BATCHES_PER_ITER)):
        run_batches(NUM_BATCHES_PER_ITER)

    # Timed iterations (reference :94-101).
    img_secs = []
    for _ in range(NUM_ITERS):
        t0 = time.perf_counter()
        run_batches(NUM_BATCHES_PER_ITER)
        dt = time.perf_counter() - t0
        img_secs.append(batch * NUM_BATCHES_PER_ITER / dt)

    per_chip = float(np.mean(img_secs)) / n
    print(json.dumps({
        "metric": "resnet50_synthetic_img_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
