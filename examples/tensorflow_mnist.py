#!/usr/bin/env python
"""TensorFlow MNIST through the TF shim — the TPU-native equivalent of
examples/tensorflow_mnist.py + tensorflow_mnist_estimator.py (graph-mode
training with DistributedOptimizer, broadcast at start, rank-0-only
checkpointing).

TF computes the model; the collectives ride the XLA data plane through
py_function hooks (graph-safe).
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)

# tf.keras IS Keras 3 and honors KERAS_BACKEND; a stray
# "torch"/"jax" value from the environment would silently run
# this TF example on another backend and break GradientTape.
os.environ["KERAS_BACKEND"] = "tensorflow"

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd

from _data import synthetic_mnist, shard_for_rank  # noqa: E402

BATCH = 64
STEPS = int(os.environ.get("STEPS", 100))
CKPT = os.environ.get("CKPT_DIR", "/tmp/hvd_tpu_tf_mnist")


def main():
    hvd.init()

    images, labels = synthetic_mnist()
    images, labels = shard_for_rank((images, labels),
                                    hvd.rank(), hvd.size())
    images = images.reshape(-1, 784)

    model = tf.keras.Sequential([
        tf.keras.layers.Reshape((28, 28, 1), input_shape=(784,)),
        tf.keras.layers.Conv2D(32, 5, padding="same", activation="relu"),
        tf.keras.layers.MaxPooling2D(2),
        tf.keras.layers.Conv2D(64, 5, padding="same", activation="relu"),
        tf.keras.layers.MaxPooling2D(2),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(1024, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    model.build((None, 784))

    # LR scaled by size; optimizer wrapped (reference :103-108).
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(0.01 * hvd.size()))

    loss_obj = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)

    @tf.function
    def train_step(x, y):
        with tf.GradientTape() as tape:
            loss = loss_obj(y, model(x, training=True))
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        return loss

    # Broadcast initial variables from rank 0 (the hook's job,
    # tensorflow/__init__.py:117-148).
    hvd.broadcast_variables(model.variables, root_rank=0)

    n = images.shape[0]
    batch = min(BATCH, n)
    for step in range(STEPS):
        i = (step * batch) % (n - batch + 1)
        loss = train_step(tf.constant(images[i:i + batch]),
                          tf.constant(labels[i:i + batch]))
        if step % 20 == 0 and hvd.rank() == 0:
            print(f"step {step:4d}  loss {float(loss):.4f}")

    # Checkpoint on rank 0 only (reference: checkpoint_dir gated on rank).
    if hvd.rank() == 0:
        os.makedirs(CKPT, exist_ok=True)
        model.save_weights(os.path.join(CKPT, "model.weights.h5"))
        print(f"checkpoint written to {CKPT}")


if __name__ == "__main__":
    main()
