#!/usr/bin/env python
"""PyTorch synthetic throughput benchmark through the torch shim — the
TPU-native equivalent of examples/pytorch_synthetic_benchmark.py (~100
LoC): torchvision model on random data, warmup then timed iterations,
img/sec mean +- 1.96 sigma.
"""

import argparse
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # repo root (uninstalled runs)

import numpy as np
import torch
import torch.nn.functional as F
import torch.utils.data

import horovod_tpu.torch as hvd


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet18")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--num-warmup-batches", type=int, default=2)
    p.add_argument("--num-batches-per-iter", type=int, default=3)
    p.add_argument("--num-iters", type=int, default=3)
    p.add_argument("--image-size", type=int, default=64)
    return p.parse_args()


def main():
    args = parse_args()
    hvd.init()

    from _data import torch_image_model
    model, args.model = torch_image_model(args.model)

    opt = torch.optim.SGD(model.parameters(), lr=0.01 * hvd.size())
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    data = torch.randn(args.batch_size, 3, args.image_size, args.image_size)
    target = torch.randint(0, 100, (args.batch_size,))

    def benchmark_step():
        opt.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        opt.step()

    if hvd.rank() == 0:
        print(f"Model: {args.model}, batch {args.batch_size}/proc x "
              f"{hvd.size()} procs")
    for _ in range(args.num_warmup_batches):
        benchmark_step()

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            benchmark_step()
        dt = time.perf_counter() - t0
        rate = args.batch_size * args.num_batches_per_iter / dt
        if hvd.rank() == 0:
            print(f"Iter #{i}: {rate:.1f} img/sec per proc")
        img_secs.append(rate)

    if hvd.rank() == 0:
        mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
        print(f"Img/sec per proc: {mean:.1f} +- {conf:.1f}")
        print(f"Total img/sec on {hvd.size()} proc(s): "
              f"{hvd.size() * mean:.1f} +- {hvd.size() * conf:.1f}")


if __name__ == "__main__":
    main()
