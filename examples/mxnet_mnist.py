#!/usr/bin/env python
"""MXNet-style MNIST through the mxnet shim — the TPU-native equivalent
of examples/mxnet_mnist.py (142 LoC): DistributedOptimizer wrapping the
base optimizer's update(), broadcast_parameters before training.

Runs against real MXNet when installed; otherwise against the bundled
NDArray protocol (a simple linear model trained with manual gradients, so
the example stays runnable without the MXNet engine).
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)

import numpy as np

import horovod_tpu.mxnet as hvd
from horovod_tpu.mxnet import nd

from _data import synthetic_mnist, shard_for_rank  # noqa: E402

BATCH = 64
EPOCHS = int(os.environ.get("EPOCHS", 2))


class SGD:
    """mx.optimizer.SGD-shaped stub used when MXNet is absent."""

    def __init__(self, learning_rate=0.05):
        self.learning_rate = learning_rate

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        if isinstance(index, (tuple, list)):
            for w, g in zip(weight, grad):
                w[:] = w.asnumpy() - self.learning_rate * g.asnumpy()
        else:
            weight[:] = (weight.asnumpy()
                         - self.learning_rate * grad.asnumpy())

    def set_learning_rate(self, lr):
        self.learning_rate = lr


def softmax_xent_grads(W, b, x, y):
    """Loss + gradients of a linear softmax classifier, by hand — the
    NDArray-protocol path has no autograd engine."""
    logits = x @ W.asnumpy() + b.asnumpy()
    logits -= logits.max(axis=1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(axis=1, keepdims=True)
    n = x.shape[0]
    loss = -np.log(p[np.arange(n), y] + 1e-9).mean()
    dlogits = p
    dlogits[np.arange(n), y] -= 1.0
    dlogits /= n
    return loss, nd.array(x.T @ dlogits), nd.array(dlogits.sum(axis=0))


def main():
    hvd.init()

    images, labels = synthetic_mnist()
    images, labels = shard_for_rank((images, labels),
                                    hvd.rank(), hvd.size())
    x_all = images.reshape(images.shape[0], -1)

    rng = np.random.RandomState(0)
    params = {"weight": nd.array(rng.randn(784, 10) * 0.01,
                                 dtype=np.float32),
              "bias": nd.array(np.zeros(10), dtype=np.float32)}

    # Sync initial params from rank 0 (reference :108-112).
    hvd.broadcast_parameters(params, root_rank=0)

    # Wrap the optimizer: update() allreduces grads first (reference :100).
    opt = hvd.DistributedOptimizer(SGD(learning_rate=0.05 * hvd.size()))

    n = x_all.shape[0]
    step = 0
    for epoch in range(EPOCHS):
        for i in range(0, n - BATCH + 1, BATCH):
            x, y = x_all[i:i + BATCH], labels[i:i + BATCH]
            loss, gw, gb = softmax_xent_grads(params["weight"],
                                              params["bias"], x, y)
            opt.update([2 * step, 2 * step + 1],
                       [params["weight"], params["bias"]], [gw, gb],
                       [None, None])
            step += 1
        logits = x_all @ params["weight"].asnumpy() + params["bias"].asnumpy()
        acc = float((logits.argmax(1) == labels).mean())
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {loss:.4f} acc {acc:.4f}")


if __name__ == "__main__":
    main()
