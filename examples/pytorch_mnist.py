#!/usr/bin/env python
"""PyTorch MNIST through the torch shim — the TPU-native equivalent of
examples/pytorch_mnist.py (166 LoC): DistributedSampler-style sharding,
DistributedOptimizer with per-parameter async allreduce hooks,
broadcast_parameters + broadcast_optimizer_state at start, averaged
metrics at epoch end.

Torch runs the autograd/optimizer; the collectives ride the XLA data
plane.
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd

from _data import synthetic_mnist, shard_for_rank  # noqa: E402

BATCH = 64
EPOCHS = int(os.environ.get("EPOCHS", 2))


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 32, 5)
        self.conv2 = nn.Conv2d(32, 64, 5)
        self.fc1 = nn.Linear(64 * 4 * 4, 512)
        self.fc2 = nn.Linear(512, 10)

    def forward(self, x):
        x = F.max_pool2d(F.relu(self.conv1(x)), 2)
        x = F.max_pool2d(F.relu(self.conv2(x)), 2)
        x = x.flatten(1)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def metric_average(val: float, name: str) -> float:
    t = torch.tensor(val)
    return hvd.allreduce(t, average=True, name=name).item()


def main():
    hvd.init()
    torch.manual_seed(42 + hvd.rank())

    images, labels = synthetic_mnist()
    images, labels = shard_for_rank((images, labels),
                                    hvd.rank(), hvd.size())
    x = torch.from_numpy(np.transpose(images, (0, 3, 1, 2)))
    y = torch.from_numpy(labels.astype(np.int64))

    model = Net()
    # LR scaled by world size (reference :94-97).
    opt = torch.optim.SGD(model.parameters(), lr=0.01 * hvd.size(),
                          momentum=0.5)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())

    # State sync from rank 0 (reference :99-101).
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    n = x.shape[0]
    for epoch in range(EPOCHS):
        model.train()
        perm = torch.randperm(n)
        for i in range(0, n - BATCH + 1, BATCH):
            idx = perm[i:i + BATCH]
            opt.zero_grad()
            loss = F.nll_loss(model(x[idx]), y[idx])
            loss.backward()      # async allreduce fires per gradient
            opt.step()           # synchronizes handles, then updates
        model.eval()
        with torch.no_grad():
            out = model(x[:512])
            test_loss = F.nll_loss(out, y[:512]).item()
            acc = (out.argmax(1) == y[:512]).float().mean().item()
        # Average metrics over ranks (reference metric_average :129-133).
        test_loss = metric_average(test_loss, f"avg_loss.{epoch}")
        acc = metric_average(acc, f"avg_acc.{epoch}")
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {test_loss:.4f} acc {acc:.4f}")


if __name__ == "__main__":
    main()
