#!/usr/bin/env python
"""TensorFlow MNIST with a MonitoredTrainingSession + SessionRunHook —
the TPU-native equivalent of examples/tensorflow_mnist_estimator.py (214
LoC: Estimator training with BroadcastGlobalVariablesHook) and the
hook-based half of examples/tensorflow_mnist.py.

The reference attaches ``hvd.BroadcastGlobalVariablesHook(0)`` so every
worker starts from rank 0's initial weights (tensorflow/__init__.py:
117-148); rank 0 alone writes checkpoints. This mirrors that session/
hook training loop on a TF1-compat graph: the hook broadcasts all global
variables after session creation, the DistributedOptimizer averages
gradients through ONE bridged engine group per step, and only rank 0
passes a checkpoint_dir.
"""

import os
import sys
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root

os.environ["KERAS_BACKEND"] = "tensorflow"

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd

from _data import synthetic_mnist, shard_for_rank  # noqa: E402

BATCH = 64
STEPS = int(os.environ.get("STEPS", 60))
# Fresh run directory by default: a persisted global_step from a prior
# run would make StopAtStepHook stop the restored session immediately.
CKPT = os.environ.get("CKPT_DIR") or tempfile.mkdtemp(
    prefix="hvd_tpu_tf_mnist_estimator.")


def main():
    hvd.init()

    images, labels = synthetic_mnist()
    images, labels = shard_for_rank((images, labels),
                                    hvd.rank(), hvd.size())
    images = images.reshape(-1, 784).astype(np.float32)
    labels = labels.astype(np.int32)

    tf.compat.v1.disable_eager_execution()
    graph = tf.Graph()
    with graph.as_default():
        x = tf.compat.v1.placeholder(tf.float32, [None, 784], name="x")
        y = tf.compat.v1.placeholder(tf.int32, [None], name="y")

        w1 = tf.compat.v1.get_variable(
            "w1", [784, 128],
            initializer=tf.compat.v1.glorot_uniform_initializer(
                seed=hvd.rank()))  # per-rank init: the hook must fix this
        b1 = tf.compat.v1.get_variable(
            "b1", [128], initializer=tf.compat.v1.zeros_initializer())
        w2 = tf.compat.v1.get_variable(
            "w2", [128, 10],
            initializer=tf.compat.v1.glorot_uniform_initializer(
                seed=100 + hvd.rank()))
        b2 = tf.compat.v1.get_variable(
            "b2", [10], initializer=tf.compat.v1.zeros_initializer())

        hidden = tf.nn.relu(x @ w1 + b1)
        logits = hidden @ w2 + b2
        loss = tf.reduce_mean(
            tf.nn.sparse_softmax_cross_entropy_with_logits(
                labels=y, logits=logits))

        # Scale LR by world size, as the reference example does; the v1
        # optimizer path exercises the reference's compute_gradients
        # override (tensorflow/__init__.py:151-249).
        opt = hvd.DistributedOptimizer(
            tf.compat.v1.train.GradientDescentOptimizer(
                0.05 * hvd.size()))
        global_step = tf.compat.v1.train.get_or_create_global_step()
        train_op = opt.minimize(loss, global_step=global_step)

        hooks = [
            # Sync initial state from rank 0 (the reference's hook).
            hvd.BroadcastGlobalVariablesHook(0),
            tf.compat.v1.train.StopAtStepHook(last_step=STEPS),
        ]

        # Rank 0 alone writes checkpoints (SURVEY.md §5.4 convention).
        ckpt_dir = CKPT if hvd.rank() == 0 else None
        rng = np.random.RandomState(hvd.rank())
        losses = []
        with tf.compat.v1.train.MonitoredTrainingSession(
                checkpoint_dir=ckpt_dir, hooks=hooks,
                config=tf.compat.v1.ConfigProto()) as sess:
            while not sess.should_stop():
                idx = rng.randint(0, len(images), BATCH)
                l, _ = sess.run(
                    [loss, train_op],
                    feed_dict={x: images[idx], y: labels[idx]})
                losses.append(l)

    print(f"rank {hvd.rank()}: first loss {losses[0]:.4f}, "
          f"final loss {losses[-1]:.4f}")
    assert np.isfinite(losses).all(), "loss diverged"
    if STEPS >= 30:  # too few steps to demand progress in smoke runs
        assert min(losses) < losses[0], "loss did not decrease"
    print("DONE")


if __name__ == "__main__":
    main()
