#!/usr/bin/env python
"""Synthetic throughput benchmark, model-selectable — the TPU-native
equivalent of examples/tensorflow_synthetic_benchmark.py (120 LoC:
Keras-applications model on random data, 10 warmup batches, 10x10 timed
batches, img/sec mean +- 1.96 sigma).

Input rides the real pipeline (docs/data.md): a ``data.synthetic()``
image source through the sharded loader with prefetch-to-device — NOT a
pre-staged device constant — so the run exercises (and the StepTimer +
``tools/trace report`` attribute) the same input/h2d path a real
dataset would. A deliberately slow source here flips the trace-report
verdict to input-bound; prefetch hides it again.

    python examples/jax_synthetic_benchmark.py --model ResNet50
    python examples/jax_synthetic_benchmark.py --model VGG16 --batch-size 32
    python examples/jax_synthetic_benchmark.py --no-prefetch  # staging A/B
"""

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import data as hvd_data
from horovod_tpu import models as zoo


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="ResNet50",
                   choices=["ResNet50", "ResNet101", "ResNet152",
                            "VGG16", "VGG19", "InceptionV3"])
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-warmup-batches", type=int, default=10)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--dataset-size", type=int, default=None,
                   help="synthetic dataset size (default: enough for "
                        "one run without epoch wrap)")
    p.add_argument("--no-prefetch", action="store_true",
                   help="stage batches synchronously instead of the "
                        "double-buffered prefetch-to-device path")
    p.add_argument("--prefetch-depth", type=int, default=2)
    return p.parse_args()


def main():
    args = parse_args()
    hvd.init()
    n = hvd.size()
    mesh = hvd.mesh()
    image_size = args.image_size or (299 if args.model == "InceptionV3"
                                     else 224)

    model = getattr(zoo, args.model)(num_classes=1000)
    batch = args.batch_size * n

    # The real input path (docs/data.md): synthetic SOURCE -> sharded
    # loader -> prefetch-to-device, with the StepTimer attributing
    # input vs h2d vs compute per step.
    n_samples = args.dataset_size or max(
        batch * (args.num_warmup_batches
                 + args.num_iters * args.num_batches_per_iter + 2),
        4 * batch)
    src = hvd_data.synthetic("image", n=n_samples,
                             image_size=image_size, num_classes=1000,
                             seed=1234)
    loader = hvd_data.build_loader(src, batch_size=batch, rank=0,
                                   world_size=1, seed=0)

    rng = jax.random.PRNGKey(0)
    tmpl = src.take(np.arange(2))
    variables = model.init({"params": rng, "dropout": rng},
                           jnp.asarray(tmpl[0]), train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})

    params = hvd.broadcast_parameters(params, root_rank=0)
    opt = hvd.DistributedGradientTransformation(
        optax.sgd(0.01 * n, momentum=0.9))
    opt_state = opt.init(params)

    has_bn = bool(batch_stats)
    sharding = NamedSharding(mesh, P("dp")) if n > 1 else None

    from functools import partial

    from horovod_tpu.observability import StepTimer

    timer = StepTimer("jax_synthetic", batch_size=batch)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_step(params, batch_stats, opt_state, x, y, i):
        r = jax.random.fold_in(rng, i)

        def loss_fn(p):
            var = {"params": p}
            if has_bn:
                var["batch_stats"] = batch_stats
                logits, new = model.apply(var, x, train=True,
                                          rngs={"dropout": r},
                                          mutable=["batch_stats"])
                return (optax
                        .softmax_cross_entropy_with_integer_labels(
                            logits, y).mean(), new["batch_stats"])
            logits = model.apply(var, x, train=True,
                                 rngs={"dropout": r})
            return (optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean(), batch_stats)

        (_, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, new_opt = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_bs, new_opt

    if args.no_prefetch:
        it = iter(loader)
    else:
        it = hvd_data.prefetch_to_device(loader, sharding,
                                         depth=args.prefetch_depth,
                                         timer=timer)

    step_idx = 0

    def run(k):
        nonlocal params, batch_stats, opt_state, step_idx
        for _ in range(k):
            b = next(it)
            timer.begin()
            if args.no_prefetch:
                b = hvd_data.stage(b, sharding, timer=timer)
            params, batch_stats, opt_state = train_step(
                params, batch_stats, opt_state, b.data[0], b.data[1],
                step_idx)
            step_idx += 1
            # device-to-host read: the only reliable full sync
            float(jnp.sum(jax.tree_util.tree_leaves(params)[0]))
            timer.end()

    if hvd.rank() == 0:
        print(f"Model: {args.model}, batch {args.batch_size}/chip x "
              f"{n} chips, dataset {n_samples} samples, prefetch "
              f"{'off' if args.no_prefetch else args.prefetch_depth}")
    for _ in range(-(-args.num_warmup_batches
                     // args.num_batches_per_iter)):
        run(args.num_batches_per_iter)  # warmup (reference :88-92)

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        run(args.num_batches_per_iter)
        dt = time.perf_counter() - t0
        rate = batch * args.num_batches_per_iter / dt
        if hvd.rank() == 0:
            print(f"Iter #{i}: {rate:.1f} img/sec total")
        img_secs.append(rate)

    if not args.no_prefetch:
        it.close()
    if hvd.rank() == 0:
        mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
        ph = timer.last_phases
        print(f"Img/sec total: {mean:.1f} +- {conf:.1f}  "
              f"({mean / n:.1f}/chip on {n} chips)")
        print("Last-step attribution (hvdtpu_step_phase_seconds): "
              + ", ".join(f"{p}={ph[p] * 1e3:.1f}ms" for p in
                          ("input", "h2d", "compute", "collective")))


if __name__ == "__main__":
    main()
