#!/usr/bin/env python
"""Synthetic throughput benchmark, model-selectable — the TPU-native
equivalent of examples/tensorflow_synthetic_benchmark.py (120 LoC:
Keras-applications model on random data, 10 warmup batches, 10x10 timed
batches, img/sec mean +- 1.96 sigma).

    python examples/jax_synthetic_benchmark.py --model ResNet50
    python examples/jax_synthetic_benchmark.py --model VGG16 --batch-size 32
"""

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import models as zoo

from _data import synthetic_imagenet  # noqa: E402


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="ResNet50",
                   choices=["ResNet50", "ResNet101", "ResNet152",
                            "VGG16", "VGG19", "InceptionV3"])
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-warmup-batches", type=int, default=10)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--image-size", type=int, default=None)
    return p.parse_args()


def main():
    args = parse_args()
    hvd.init()
    n = hvd.size()
    mesh = hvd.mesh()
    image_size = args.image_size or (299 if args.model == "InceptionV3"
                                     else 224)

    model = getattr(zoo, args.model)(num_classes=1000)
    batch = args.batch_size * n
    images_np, labels_np = synthetic_imagenet(batch, image_size)
    rng = jax.random.PRNGKey(0)
    variables = model.init({"params": rng, "dropout": rng},
                           jnp.asarray(images_np[:2]), train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})

    params = hvd.broadcast_parameters(params, root_rank=0)
    opt = hvd.DistributedGradientTransformation(
        optax.sgd(0.01 * n, momentum=0.9))
    opt_state = opt.init(params)

    images = jnp.asarray(images_np)
    labels = jnp.asarray(labels_np)
    if n > 1:
        images = jax.device_put(images, NamedSharding(mesh, P("dp")))
        labels = jax.device_put(labels, NamedSharding(mesh, P("dp")))

    has_bn = bool(batch_stats)

    from functools import partial

    # One jitted fori_loop per timed iteration (k optimizer steps, one
    # dispatch) with donated state — same levers as bench.py; host
    # latency stays out of the measured device time.
    @partial(jax.jit, donate_argnums=(0, 1, 2), static_argnums=(5,))
    def train_k(params, batch_stats, opt_state, x, y, k):
        def body(i, carry):
            params, batch_stats, opt_state = carry
            r = jax.random.fold_in(rng, i)

            def loss_fn(p):
                var = {"params": p}
                if has_bn:
                    var["batch_stats"] = batch_stats
                    logits, new = model.apply(var, x, train=True,
                                              rngs={"dropout": r},
                                              mutable=["batch_stats"])
                    return (optax
                            .softmax_cross_entropy_with_integer_labels(
                                logits, y).mean(), new["batch_stats"])
                logits = model.apply(var, x, train=True,
                                     rngs={"dropout": r})
                return (optax.softmax_cross_entropy_with_integer_labels(
                    logits, y).mean(), batch_stats)

            (_, new_bs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, new_opt = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_bs, new_opt

        return jax.lax.fori_loop(0, k, body,
                                 (params, batch_stats, opt_state))

    def run(k):
        nonlocal params, batch_stats, opt_state
        params, batch_stats, opt_state = train_k(
            params, batch_stats, opt_state, images, labels, k)
        # device-to-host read: the only reliable full sync
        float(jnp.sum(jax.tree_util.tree_leaves(params)[0]))

    if hvd.rank() == 0:
        print(f"Model: {args.model}, batch {args.batch_size}/chip x "
              f"{n} chips")
    # Warmup with the SAME static k as the timed iterations so the
    # timed executable is compiled before measurement (a different k
    # would be a separate trace+compile landing inside iter #0).
    # --num-warmup-batches 0 measures cold-start compile; other values
    # round UP to whole iterations (announced, not silent).
    warmup_calls = -(-args.num_warmup_batches // args.num_batches_per_iter)
    actual = warmup_calls * args.num_batches_per_iter
    if hvd.rank() == 0 and actual != args.num_warmup_batches:
        print(f"warmup rounded to {actual} batches "
              f"({warmup_calls} x {args.num_batches_per_iter})")
    for _ in range(warmup_calls):
        run(args.num_batches_per_iter)  # warmup (reference :88-92)

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        run(args.num_batches_per_iter)
        dt = time.perf_counter() - t0
        rate = batch * args.num_batches_per_iter / dt
        if hvd.rank() == 0:
            print(f"Iter #{i}: {rate:.1f} img/sec total")
        img_secs.append(rate)

    if hvd.rank() == 0:
        mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
        print(f"Img/sec total: {mean:.1f} +- {conf:.1f}  "
              f"({mean / n:.1f}/chip on {n} chips)")


if __name__ == "__main__":
    main()
