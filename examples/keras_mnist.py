#!/usr/bin/env python
"""Keras MNIST with the Horovod pattern — the TPU-native equivalent of
examples/keras_mnist.py: DistributedOptimizer + broadcast callback +
rank-0-only checkpointing, on Keras 3.

    KERAS_BACKEND=torch python examples/keras_mnist.py
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)

os.environ.setdefault("KERAS_BACKEND", "torch")

import keras  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
import horovod_tpu.keras as hvd_keras  # noqa: E402
import horovod_tpu.keras.callbacks as hvd_callbacks  # noqa: E402

from _data import synthetic_mnist, shard_for_rank  # noqa: E402

EPOCHS = int(os.environ.get("EPOCHS", 2))


def main():
    hvd.init()

    images, labels = synthetic_mnist()
    (x_train, y_train) = shard_for_rank((images, labels),
                                        hvd.rank(), hvd.size())

    model = keras.Sequential([
        keras.layers.Input((28, 28, 1)),
        keras.layers.Conv2D(32, (5, 5), activation="relu"),
        keras.layers.MaxPooling2D((2, 2)),
        keras.layers.Conv2D(64, (5, 5), activation="relu"),
        keras.layers.MaxPooling2D((2, 2)),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dropout(0.5),
        keras.layers.Dense(10, activation="softmax"),
    ])

    # LR scaled by world size; optimizer wrapped so grads are averaged.
    opt = hvd_keras.DistributedOptimizer(
        keras.optimizers.Adadelta(learning_rate=1.0 * hvd.size()))
    model.compile(optimizer=opt,
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], jit_compile=False)

    callbacks = [
        # Sync initial weights from rank 0 (keras_mnist.py callback list).
        hvd_callbacks.BroadcastGlobalVariablesCallback(0),
        hvd_callbacks.MetricAverageCallback(),
    ]
    # Checkpoint on rank 0 only.
    if hvd.rank() == 0:
        os.makedirs("/tmp/hvd_tpu_keras_mnist", exist_ok=True)
        callbacks.append(keras.callbacks.ModelCheckpoint(
            "/tmp/hvd_tpu_keras_mnist/checkpoint.weights.h5",
            save_weights_only=True))

    model.fit(x_train, y_train, batch_size=64, epochs=EPOCHS,
              callbacks=callbacks, verbose=1 if hvd.rank() == 0 else 0)

    score = model.evaluate(x_train[:512], y_train[:512], verbose=0)
    if hvd.rank() == 0:
        print(f"loss {score[0]:.4f}  accuracy {score[1]:.4f}")


if __name__ == "__main__":
    main()
