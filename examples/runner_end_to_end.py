#!/usr/bin/env python
"""Cluster-launcher end-to-end example — the TPU-native equivalent of
examples/keras_spark_rossmann.py's orchestration skeleton (556 LoC:
Spark ETL -> horovod.spark.run(fn) training -> inference collection).

Spark's role (cluster launcher + result collection) is played by
``horovod_tpu.runner.run``: preprocess on the driver, ship a pickled
training fn to np worker processes (local or ssh-remote), train
data-parallel, collect per-rank results in rank order, then "serve"
predictions on the driver from rank 0's returned parameters.
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)

import numpy as np


def train_fn(features, targets, epochs=20):
    """Runs inside each launched worker process."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd

    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    # Shard the driver-prepared dataset by rank.
    n = features.shape[0] // size
    x = jnp.asarray(features[rank * n:(rank + 1) * n])
    y = jnp.asarray(targets[rank * n:(rank + 1) * n])

    params = {"w": jnp.zeros((x.shape[1],)), "b": jnp.asarray(0.0)}
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt = hvd.DistributedGradientTransformation(optax.sgd(0.1))
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            pred = x @ p["w"] + p["b"]
            return jnp.mean((pred - y) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, state2 = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state2, loss

    for _ in range(epochs):
        params, state, loss = step(params, state)
    return {"rank": rank, "loss": float(loss),
            "params": jax.device_get(params)}


def main():
    from horovod_tpu.runner import run

    # "ETL" on the driver: build a regression dataset (the Rossmann
    # example engineers features in Spark; numpy plays that role here).
    rng = np.random.RandomState(0)
    features = rng.randn(1024, 8).astype(np.float32)
    true_w = rng.randn(8).astype(np.float32)
    targets = features @ true_w + 0.5

    np_procs = int(os.environ.get("NP", 2))
    results = run(train_fn, args=(features, targets), np=np_procs)

    # Collect in rank order (spark/__init__.py:191-196 semantics).
    for r in results:
        print(f"rank {r['rank']}: final train mse {r['loss']:.5f}")

    # "Inference" on the driver with rank 0's parameters.
    params = results[0]["params"]
    preds = features[:5] @ params["w"] + params["b"]
    print("sample predictions:", np.round(preds, 3))
    print("sample targets:    ", np.round(targets[:5], 3))


if __name__ == "__main__":
    main()
