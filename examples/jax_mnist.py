#!/usr/bin/env python
"""MNIST training in plain JAX with the 5-line Horovod pattern — the
TPU-native equivalent of examples/tensorflow_mnist.py (161 LoC:
MonitoredTrainingSession + BroadcastGlobalVariablesHook + rank-0-only
checkpointing).

Run single-host multi-device:
    python examples/jax_mnist.py
Run multi-process:
    python -m horovod_tpu.runner -np 2 python examples/jax_mnist.py
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import MnistConvNet

from _data import synthetic_mnist, shard_for_rank

BATCH = 64
STEPS = int(os.environ.get("STEPS", 60))
CKPT = os.environ.get("CKPT_DIR", "/tmp/hvd_tpu_mnist")


def main():
    # Horovod step 1: initialize (reference usage step 1).
    hvd.init()

    images, labels = synthetic_mnist()
    # Shard the dataset by rank (reference step: shard your data).
    images, labels = shard_for_rank((images, labels), hvd.rank(), hvd.size())

    model = MnistConvNet()
    rng = jax.random.PRNGKey(42)
    params = model.init({"params": rng}, jnp.ones((1, 28, 28, 1)),
                        train=False)["params"]

    # Step 2: scale the learning rate by world size (reference step 3).
    opt = hvd.DistributedOptimizer(optax.sgd(0.01 * hvd.size(),
                                             momentum=0.9))
    opt_state = opt.init(params)

    # Step 3: broadcast initial state from rank 0 so all ranks agree
    # (reference step 5 — BroadcastGlobalVariablesHook).
    params = hvd.broadcast_parameters(params, root_rank=0)

    @jax.jit
    def train_step(params, opt_state, x, y, step_rng):
        def loss_fn(p):
            logits = model.apply({"params": p}, x, train=True,
                                 rngs={"dropout": step_rng})
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # hvd.DistributedOptimizer averages grads over the mesh in here.
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    n = images.shape[0]
    batch = min(BATCH, n)
    for step in range(STEPS):
        i = (step * batch) % (n - batch + 1)
        x = jnp.asarray(images[i:i + batch])
        y = jnp.asarray(labels[i:i + batch])
        params, opt_state, loss = train_step(
            params, opt_state, x, y, jax.random.fold_in(rng, step))
        if step % 10 == 0 and hvd.rank() == 0:
            print(f"step {step:4d}  loss {float(loss):.4f}")

    # Step 4: checkpoint on rank 0 only (reference step 6).
    if hvd.rank() == 0:
        os.makedirs(CKPT, exist_ok=True)
        with open(os.path.join(CKPT, "params.pkl"), "wb") as f:
            pickle.dump(jax.device_get(params), f)
        print(f"checkpoint written to {CKPT}")


if __name__ == "__main__":
    main()
