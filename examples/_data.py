"""Shared synthetic data for the examples.

The reference examples download MNIST/ImageNet; this environment has no
network egress, so examples train on a *learnable* synthetic stand-in:
each class is a Gaussian blob around a fixed random prototype image, so
losses genuinely decrease and accuracy genuinely rises — the distributed
mechanics being demonstrated are identical.

Every example shards data by rank exactly the way the reference does with
``tf.data.shard`` / ``DistributedSampler`` (examples/pytorch_mnist.py:43-64).
"""

from __future__ import annotations

import os

import numpy as np

# Make JAX_PLATFORMS authoritative for example runs: a site customization
# (e.g. a TPU tunnel plugin) may have already pinned jax_platforms, which
# outranks the env var. Examples import this module before first JAX use,
# so re-asserting here lets `JAX_PLATFORMS=cpu python examples/...` work
# the way the docs promise (same re-assert as runner/task_exec.py:25-32).
if os.environ.get("JAX_PLATFORMS"):
    try:
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass


def synthetic_mnist(n: int = 4096, num_classes: int = 10, seed: int = 1234,
                    image_shape=(28, 28, 1)):
    """(images [n,*image_shape] float32 in [0,1], labels [n] int32)."""
    rng = np.random.RandomState(seed)
    protos = rng.rand(num_classes, *image_shape).astype(np.float32)
    labels = rng.randint(0, num_classes, size=n).astype(np.int32)
    images = protos[labels] + 0.3 * rng.randn(n, *image_shape).astype(
        np.float32)
    return np.clip(images, 0.0, 1.0), labels


def shard_for_rank(arrays, rank: int, size: int):
    """Contiguous per-rank shard of each array — the DistributedSampler
    pattern (examples/pytorch_mnist.py:43-64)."""
    n = arrays[0].shape[0]
    per = n // size
    sl = slice(rank * per, (rank + 1) * per)
    return tuple(a[sl] for a in arrays)


def torch_image_model(name: str, num_classes: int = 100):
    """torchvision model when available (the reference's PyTorch examples
    use torchvision); otherwise a small in-file conv net so the example
    still runs — returns (model, actual_name) with the fallback clearly
    relabeled so its numbers/checkpoints are never mistaken for the
    requested model's."""
    try:
        import torchvision.models as tvm
        return getattr(tvm, name)(num_classes=num_classes), name
    except ImportError:
        import torch.nn as nn
        model = nn.Sequential(
            nn.Conv2d(3, 32, 3, stride=2, padding=1), nn.ReLU(),
            nn.Conv2d(32, 64, 3, stride=2, padding=1), nn.ReLU(),
            nn.AdaptiveAvgPool2d(1), nn.Flatten(),
            nn.Linear(64, num_classes))
        actual = f"tiny-convnet (torchvision missing; NOT {name})"
        print(f"torchvision not installed: training {actual}")
        return model, actual


def synthetic_imagenet(batch: int, image_size: int = 224, classes: int = 1000,
                       seed: int = 0):
    """Random images/labels for throughput benchmarks (the reference's
    synthetic benchmark uses pure random data,
    examples/tensorflow_synthetic_benchmark.py:60-66)."""
    rng = np.random.RandomState(seed)
    images = rng.rand(batch, image_size, image_size, 3).astype(np.float32)
    labels = rng.randint(0, classes, size=batch).astype(np.int32)
    return images, labels


def text8_like_tokens(n: int = 100_000, vocab: int = 5000, seed: int = 7):
    """Zipf-distributed token stream standing in for the word2vec corpus
    (examples/tensorflow_word2vec.py downloads text8)."""
    rng = np.random.RandomState(seed)
    tokens = rng.zipf(1.3, size=n).astype(np.int64)
    return np.clip(tokens, 0, vocab - 1).astype(np.int32)
