#!/usr/bin/env python
"""MXNet-style ResNet-50 training through the mxnet shim — the TPU-native
equivalent of examples/mxnet_imagenet_resnet50.py (456 LoC: Module and
Gluon paths, fp16 via net.cast, warmup + staged LR).

With real MXNet installed this uses gluon ResNet; without it, the JAX
ResNet-50 computes loss/gradients and the mxnet-shim DistributedOptimizer
performs the distributed update — demonstrating that the shim's update()
path is engine-agnostic.
"""

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)

import numpy as np

import horovod_tpu.mxnet as hvd
from horovod_tpu.mxnet import nd

from _data import synthetic_imagenet  # noqa: E402


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--lr", type=float, default=0.0125)
    p.add_argument("--warmup-epochs", type=int, default=1)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--classes", type=int, default=10)
    return p.parse_args()


class SGDMom:
    def __init__(self, learning_rate, momentum=0.9):
        self.learning_rate = learning_rate
        self.momentum = momentum

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=np.float32)

    def update(self, index, weight, grad, state):
        if isinstance(index, (tuple, list)):
            for w, g, s in zip(weight, grad, state):
                self._one(w, g, s)
        else:
            self._one(weight, grad, state)

    def _one(self, w, g, s):
        s[:] = self.momentum * s.asnumpy() + g.asnumpy()
        w[:] = w.asnumpy() - self.learning_rate * s.asnumpy()

    def set_learning_rate(self, lr):
        self.learning_rate = lr


def main():
    args = parse_args()
    hvd.init()

    import jax
    import jax.numpy as jnp
    import optax
    from horovod_tpu.models import ResNet50

    model = ResNet50(num_classes=args.classes, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    imgs, lbls = synthetic_imagenet(args.batch_size, args.image_size,
                                    args.classes, seed=hvd.rank())
    variables = model.init(rng, jnp.asarray(imgs[:1]), train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    # Flatten JAX params into the NDArray world the shim operates on.
    flat, treedef = jax.tree_util.tree_flatten(params)
    weights = [nd.array(np.asarray(p), dtype=np.float32) for p in flat]
    hvd.broadcast_parameters({str(i): w for i, w in enumerate(weights)},
                             root_rank=0)

    opt = hvd.DistributedOptimizer(
        SGDMom(learning_rate=args.lr * hvd.size()))
    states = [opt.create_state(i, w) for i, w in enumerate(weights)]

    @jax.jit
    def grads_fn(params, batch_stats, x, y):
        def loss_fn(p):
            logits, new = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            return (optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean(), new["batch_stats"])
        (loss, bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        return loss, bs, grads

    x, y = jnp.asarray(imgs), jnp.asarray(lbls)
    steps = max(2, 8 // args.batch_size)
    for step in range(args.epochs * steps):
        cur = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(w.asnumpy()) for w in weights])
        loss, batch_stats, grads = grads_fn(cur, batch_stats, x, y)
        gflat = [nd.array(np.asarray(g), dtype=np.float32)
                 for g in jax.tree_util.tree_leaves(grads)]
        opt.update(list(range(len(weights))), weights, gflat, states)
        if hvd.rank() == 0:
            print(f"step {step}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
