#!/usr/bin/env python
"""End-to-end data pipeline: ETL -> rank-sharded training with
checkpoint/resume -> batch inference -> predictions file.

This is the TPU-native counterpart of the reference's largest example,
the Rossmann store-sales pipeline (examples/keras_spark_rossmann.py:
Spark ETL -> feature engineering -> distributed Keras training ->
inference writing a submission file). Same pipeline shape, JAX-native
stages, tabular regression like the original:

  1. **ETL** (rank 0): raw "sales log" records -> feature engineering
     (normalization, one-hot calendar features) -> shard files on disk
     (the Parquet-stage equivalent), with a held-out inference split.
     Other ranks wait on a barrier allreduce.
  2. **Train**: every rank reads ONLY its shard files
     (``files[rank::size]``, the DistributedSampler partition at file
     granularity), per-epoch reshuffle keyed on (seed, epoch, rank),
     initial state broadcast from rank 0, gradients averaged by
     ``hvd.DistributedGradientTransformation`` inside one jitted step;
     rank 0 writes a checkpoint every epoch (``hvd.save_checkpoint``).
  3. **Resume**: training state is rebuilt FRESH and restored from the
     last checkpoint (``hvd.restore_checkpoint`` broadcasts rank 0's
     file to all ranks — the spot-restart recipe), then training
     finishes. The resumed loss must continue from, not restart above,
     the pre-checkpoint loss.
  4. **Inference**: the final checkpoint serves batch predictions over
     the held-out shard; rank 0 writes ``predictions.csv`` (the
     submission-file stage) and prints a validation RMSPE-style metric.

Run:
    python examples/jax_pipeline_end_to_end.py
    python -m horovod_tpu.runner -np 2 python examples/jax_pipeline_end_to_end.py
"""

import glob
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]

import numpy as np

import jax

# Honor JAX_PLATFORMS even on hosts whose sitecustomize pins another
# platform after env processing (a pinned platform silently ignores
# jax.distributed under the runner; hvd.init() now detects that case
# and points here).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import optax

import horovod_tpu as hvd

BATCH = int(os.environ.get("BATCH", 128))
STEPS = int(os.environ.get("STEPS", 40))        # per epoch
EPOCHS = int(os.environ.get("EPOCHS", 2))       # pre-resume epochs
DATA_DIR = os.environ.get("DATA_DIR", "/tmp/hvd_tpu_pipeline")
CKPT_DIR = os.environ.get("CKPT_DIR",
                          os.path.join(DATA_DIR, "checkpoints"))
NUM_SHARD_FILES = 8
N_ROWS = int(os.environ.get("N_ROWS", 20000))
SEED = 4242

D_FEAT = 7 + 12  # engineered features: 7 numeric/cyclic + 12 month 1-hot


# --------------------------------------------------------------------- ETL

def etl_stage():
    """Rank 0: raw records -> engineered feature shards + held-out split
    (the Spark-DataFrame -> Parquet stage of keras_spark_rossmann.py).
    Everyone else waits on the barrier below."""
    rank = hvd.process_rank()
    done = os.path.join(DATA_DIR, "_ETL_DONE")
    # The done-marker records the ETL config: a re-run with different
    # sizing must rebuild, not silently train on stale shards.
    stamp = f"rows={N_ROWS} shards={NUM_SHARD_FILES}\n"
    if rank == 0 and os.path.exists(done):
        with open(done) as f:
            if f.read() != stamp:
                os.unlink(done)
    if rank == 0 and os.path.exists(done):
        print("[etl] reusing existing shards", flush=True)
    if rank == 0 and not os.path.exists(done):
        rng = np.random.RandomState(SEED)
        os.makedirs(DATA_DIR, exist_ok=True)
        # Raw "sales log": (store, day-of-year, promo flag, base demand)
        store = rng.randint(0, 50, N_ROWS)
        day = rng.randint(0, 365, N_ROWS)
        promo = rng.randint(0, 2, N_ROWS)
        noise = rng.randn(N_ROWS) * 0.1
        # Ground-truth generative process the model must learn.
        sales = (2.0 + 0.5 * np.sin(2 * np.pi * day / 365.0)
                 + 0.8 * promo + 0.02 * (store % 7) + noise)

        # Feature engineering: normalized store id, cyclic day-of-year
        # encoding, promo, store-weekday bucket, plus a month one-hot —
        # the continuous+categorical mix of the Rossmann features.
        month = (day * 12 // 365)
        feats = np.stack([
            store / 50.0,
            np.sin(2 * np.pi * day / 365.0),
            np.cos(2 * np.pi * day / 365.0),
            promo.astype(np.float64),
            (store % 7) / 7.0,
            day / 365.0,
            np.ones(N_ROWS),  # bias-ish constant column
        ], axis=1)
        onehot = np.eye(12)[month]
        feats = np.concatenate([feats, onehot], axis=1).astype(np.float32)
        labels = sales.astype(np.float32)

        # Held-out inference split (the Kaggle test.csv role).
        n_hold = N_ROWS // 10
        np.savez(os.path.join(DATA_DIR, "holdout.npz"),
                 feats=feats[:n_hold], labels=labels[:n_hold])
        train_f, train_y = feats[n_hold:], labels[n_hold:]
        per = len(train_y) // NUM_SHARD_FILES
        for s in range(NUM_SHARD_FILES):
            lo = s * per
            hi = len(train_y) if s == NUM_SHARD_FILES - 1 else lo + per
            np.savez(os.path.join(DATA_DIR, f"shard_{s:03d}.npz"),
                     feats=train_f[lo:hi], labels=train_y[lo:hi])
        with open(done, "w") as f:
            f.write(stamp)
        print(f"[etl] wrote {NUM_SHARD_FILES} train shards + holdout "
              f"({N_ROWS} rows)", flush=True)
    # Barrier: no rank may read shards before rank 0 finished writing.
    hvd.allreduce(jnp.zeros((1,)), average=False, name="etl.barrier")


class ShardReader:
    """files[rank::size] partition + per-(epoch, rank) reshuffle — the
    DistributedSampler pattern at file granularity (see
    jax_mnist_file_data.py for the full rationale)."""

    def __init__(self, rank: int, size: int):
        files = sorted(glob.glob(os.path.join(DATA_DIR, "shard_*.npz")))
        if len(files) < size:
            raise ValueError(f"{len(files)} shards cannot feed {size} ranks")
        self.mine = files[rank::size]
        self.rank = rank

    def epoch_batches(self, epoch: int):
        parts = [np.load(f) for f in self.mine]
        feats = np.concatenate([p["feats"] for p in parts])
        labels = np.concatenate([p["labels"] for p in parts])
        order = np.random.RandomState(
            (SEED, epoch, self.rank).__hash__() & 0x7FFFFFFF
        ).permutation(len(labels))
        for i in range(STEPS):
            idx = order[(i * BATCH) % len(order):][:BATCH]
            if len(idx) < BATCH:  # wrap the tail
                idx = np.concatenate([idx, order[:BATCH - len(idx)]])
            yield feats[idx], labels[idx]


# ------------------------------------------------------------------- model

def init_params(rng):
    k1, k2, k3 = jax.random.split(rng, 3)
    return {
        "w1": jax.random.normal(k1, (D_FEAT, 64)) * (D_FEAT ** -0.5),
        "b1": jnp.zeros((64,)),
        "w2": jax.random.normal(k2, (64, 64)) * (64 ** -0.5),
        "b2": jnp.zeros((64,)),
        "w3": jax.random.normal(k3, (64, 1)) * (64 ** -0.5),
        "b3": jnp.zeros((1,)),
    }


def predict(params, x):
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    h = jax.nn.relu(h @ params["w2"] + params["b2"])
    return (h @ params["w3"] + params["b3"])[:, 0]


def main():
    hvd.init()
    rank, nproc = hvd.process_rank(), hvd.process_count()
    etl_stage()
    reader = ShardReader(rank, nproc)

    opt = hvd.DistributedGradientTransformation(optax.adam(1e-2))

    def fresh_state():
        params = hvd.broadcast_parameters(
            init_params(jax.random.PRNGKey(SEED)), root_rank=0)
        return {"params": params, "opt": opt.init(params), "epoch": 0}

    @jax.jit
    def train_step(state, x, y):
        def loss_fn(p):
            return jnp.mean((predict(p, x) - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state["params"])
        updates, new_opt = opt.update(grads, state["opt"],
                                      state["params"])
        return {"params": optax.apply_updates(state["params"], updates),
                "opt": new_opt, "epoch": state["epoch"]}, loss

    def run_epochs(state, n_epochs):
        last = None
        for _ in range(n_epochs):
            epoch = int(state["epoch"])
            for x, y in reader.epoch_batches(epoch):
                state, loss = train_step(state, jnp.asarray(x),
                                         jnp.asarray(y))
            state["epoch"] = epoch + 1
            last = float(loss)
            if rank == 0:
                print(f"[train] epoch {epoch} loss {last:.4f}", flush=True)
            # Rank-0 checkpoint each epoch (the reference's
            # checkpoint-on-worker-0 convention).
            hvd.save_checkpoint(state, CKPT_DIR, step=epoch)
        return state, last

    # ---- train, then simulate a restart and RESUME from the checkpoint
    state, pre_loss = run_epochs(fresh_state(), EPOCHS)
    del state  # the "crash": all in-memory training state is gone

    resumed = hvd.restore_checkpoint(CKPT_DIR, step=EPOCHS - 1)
    assert int(resumed["epoch"]) == EPOCHS, resumed["epoch"]
    state, post_loss = run_epochs(resumed, 1)
    if rank == 0:
        print(f"[resume] restored epoch {EPOCHS - 1} checkpoint; "
              f"continued to loss {post_loss:.4f}", flush=True)
        # A real resume continues the descent (generous 3x guard: the
        # loss must not restart anywhere near an untrained model's).
        assert post_loss < max(3.0 * pre_loss, 0.2), (post_loss, pre_loss)

    # ---- inference from the final checkpoint over the held-out shard
    final = hvd.restore_checkpoint(CKPT_DIR, step=EPOCHS)
    hold = np.load(os.path.join(DATA_DIR, "holdout.npz"))
    preds = np.asarray(jax.jit(predict)(
        final["params"], jnp.asarray(hold["feats"])))
    if rank == 0:
        rmse = float(np.sqrt(np.mean((preds - hold["labels"]) ** 2)))
        out_csv = os.path.join(DATA_DIR, "predictions.csv")
        with open(out_csv, "w") as f:
            f.write("row,prediction\n")
            for i, p in enumerate(preds):
                f.write(f"{i},{p:.5f}\n")
        print(f"[infer] holdout RMSE {rmse:.4f}; wrote "
              f"{len(preds)} predictions to {out_csv}", flush=True)
        # The generative process has noise sigma 0.1; an untrained model
        # sits ~1.0. Anything near the noise floor means the whole
        # pipeline (ETL -> sharded train -> resume -> infer) worked.
        assert rmse < 0.5, rmse
        print("PIPELINE_OK", flush=True)

    hvd.shutdown()


if __name__ == "__main__":
    main()
