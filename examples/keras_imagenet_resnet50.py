#!/usr/bin/env python
"""Keras ResNet-50 ImageNet-style training — the TPU-native equivalent of
examples/keras_imagenet_resnet50.py (179 LoC): warmup callback + staged
LR schedule (30/60/80 epoch decay), metric averaging, rank-0 checkpoints.

Uses synthetic ImageNet-shaped data (no egress); swap in a real input
pipeline for production runs.
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)

os.environ.setdefault("KERAS_BACKEND", "torch")

import keras  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
import horovod_tpu.keras as hvd_keras  # noqa: E402
import horovod_tpu.keras.callbacks as hvd_callbacks  # noqa: E402

from _data import synthetic_imagenet  # noqa: E402

EPOCHS = int(os.environ.get("EPOCHS", 2))
BATCH = int(os.environ.get("BATCH", 8))
IMAGE = int(os.environ.get("IMAGE", 64))  # 224 for the real benchmark
CLASSES = 100


def main():
    hvd.init()

    x, y = synthetic_imagenet(BATCH * 8, IMAGE, CLASSES,
                              seed=hvd.rank())

    model = keras.applications.ResNet50(weights=None, classes=CLASSES,
                                        input_shape=(IMAGE, IMAGE, 3))

    # Reference schedule: LR = 0.0125 * size, staged decay at 30/60/80.
    base_lr = 0.0125 * hvd.size()
    opt = hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=base_lr, momentum=0.9))
    model.compile(optimizer=opt,
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], jit_compile=False)

    callbacks = [
        hvd_callbacks.BroadcastGlobalVariablesCallback(0),
        hvd_callbacks.MetricAverageCallback(),
        hvd_callbacks.LearningRateWarmupCallback(
            warmup_epochs=1, verbose=int(hvd.rank() == 0)),
        # Staged decay: x1 until 30, x0.1 until 60, x0.01 until 80, x0.001.
        hvd_callbacks.LearningRateScheduleCallback(
            1.0, start_epoch=1, end_epoch=30),
        hvd_callbacks.LearningRateScheduleCallback(
            1e-1, start_epoch=30, end_epoch=60),
        hvd_callbacks.LearningRateScheduleCallback(
            1e-2, start_epoch=60, end_epoch=80),
        hvd_callbacks.LearningRateScheduleCallback(1e-3, start_epoch=80),
    ]
    if hvd.rank() == 0:
        os.makedirs("/tmp/hvd_tpu_keras_resnet", exist_ok=True)
        callbacks.append(keras.callbacks.ModelCheckpoint(
            "/tmp/hvd_tpu_keras_resnet/ckpt-{epoch}.weights.h5",
            save_weights_only=True))

    model.fit(x, y, batch_size=BATCH, epochs=EPOCHS, callbacks=callbacks,
              verbose=1 if hvd.rank() == 0 else 0)


if __name__ == "__main__":
    main()
