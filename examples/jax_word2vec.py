#!/usr/bin/env python
"""Distributed skip-gram word2vec — the TPU-native equivalent of
examples/tensorflow_word2vec.py (249 LoC: skip-gram batches from text8,
NCE loss, data-parallel embedding training).

Each rank consumes a different stride of the token stream; gradients are
averaged through DistributedGradientTransformation.
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)

import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.models import word2vec as w2v

from _data import text8_like_tokens  # noqa: E402

VOCAB = 5000
DIM = 128
BATCH = 256
STEPS = int(os.environ.get("STEPS", 200))


def main():
    hvd.init()
    tokens = jnp.asarray(text8_like_tokens(vocab=VOCAB))

    rng = jax.random.PRNGKey(0)
    params = w2v.init_params(VOCAB, DIM, rng)
    params = hvd.broadcast_parameters(params, root_rank=0)

    opt = hvd.DistributedGradientTransformation(
        optax.adagrad(1.0))  # the reference trains NCE with SGD/Adagrad
    opt_state = opt.init(params)

    @jax.jit
    def step_fn(params, opt_state, step):
        # Rank-strided batches: rank r reads batch (step * size + r).
        centers, contexts = w2v.skipgram_batch(
            tokens, step * hvd.size() + hvd.rank(), BATCH)
        loss, grads = jax.value_and_grad(w2v.nce_loss)(
            params, centers, contexts, jax.random.fold_in(rng, step))
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for step in range(STEPS):
        params, opt_state, loss = step_fn(params, opt_state, step)
        if step % 50 == 0 and hvd.rank() == 0:
            print(f"step {step:5d}  nce loss {float(loss):.3f}")

    if hvd.rank() == 0:
        neighbors = w2v.nearest(params, jnp.arange(4), k=5)
        for i, row in enumerate(neighbors):
            print(f"token {i}: nearest {list(map(int, row))}")


if __name__ == "__main__":
    main()
