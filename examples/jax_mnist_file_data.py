#!/usr/bin/env python
"""MNIST training with a REAL rank-sharded file-reading input pipeline.

The reference's examples feed real datasets through rank-aware loaders —
``torch.utils.data.distributed.DistributedSampler`` over MNIST
(examples/pytorch_mnist.py:43-64) and an ``ImageDataGenerator`` flow over
ImageNet directories (examples/keras_imagenet_resnet50.py:119-139). This
example is that pattern for the JAX path, at file granularity:

  - the dataset lives on disk as N ``.npy`` shard files (DATA_DIR);
  - every rank reads ONLY the shard files assigned to it round-robin
    (``files[rank::size]`` — the DistributedSampler partition);
  - each epoch reshuffles with a seed derived from (base seed, epoch,
    rank), so ranks draw different, epoch-varying orders while staying
    reproducible — the ``sampler.set_epoch`` convention;
  - when DATA_DIR holds no shards (this environment has no dataset
    downloads), rank 0 materializes the synthetic stand-in to disk first
    and every rank then genuinely READS ITS SHARD FILES — the I/O path
    being demonstrated is exercised either way.

Run:
    python examples/jax_mnist_file_data.py
    python -m horovod_tpu.runner -np 2 python examples/jax_mnist_file_data.py
"""

import glob
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]

import numpy as np

import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.models import MnistConvNet

from _data import synthetic_mnist

BATCH = int(os.environ.get("BATCH", 64))
STEPS = int(os.environ.get("STEPS", 60))
EPOCHS = int(os.environ.get("EPOCHS", 2))
DATA_DIR = os.environ.get("DATA_DIR", "/tmp/hvd_tpu_mnist_shards")
NUM_SHARD_FILES = 8
SEED = 1234


class ShardedFileDataset:
    """Rank-sharded shard-file reader (the DistributedSampler pattern at
    file granularity).

    ``files[rank::size]`` partitions the shard files; ``epoch_batches``
    loads this rank's shards, shuffles with a (seed, epoch, rank)-derived
    PRNG, and yields fixed-size batches. Real datasets write many shard
    files (one per class/source/day); partitioning whole files keeps
    every byte read exactly once per epoch across the job."""

    def __init__(self, data_dir: str, rank: int, size: int,
                 seed: int = SEED):
        self.files = sorted(glob.glob(os.path.join(data_dir, "*.npz")))
        if not self.files:
            raise FileNotFoundError(f"no shard files in {data_dir}")
        if len(self.files) < size:
            raise ValueError(
                f"{len(self.files)} shard files cannot feed {size} ranks; "
                "write at least one file per rank")
        self.mine = self.files[rank::size]
        self.rank, self.size, self.seed = rank, size, seed

    def epoch_batches(self, epoch: int, batch: int):
        """Yield (images, labels) batches for one epoch, reshuffled per
        (epoch, rank) — the ``sampler.set_epoch(epoch)`` convention."""
        parts = [np.load(f) for f in self.mine]
        images = np.concatenate([p["images"] for p in parts])
        labels = np.concatenate([p["labels"] for p in parts])
        rng = np.random.RandomState(
            (self.seed * 100003 + epoch * 1009 + self.rank) % (2 ** 31))
        order = rng.permutation(len(images))
        images, labels = images[order], labels[order]
        for i in range(0, len(images) - batch + 1, batch):
            yield images[i:i + batch], labels[i:i + batch]


def materialize_synthetic_shards(data_dir: str) -> None:
    """Rank 0 writes the synthetic stand-in dataset as shard files (no
    dataset downloads in this environment); other ranks wait for the
    completion marker. Real deployments skip this: DATA_DIR already
    holds the dataset's shard files."""
    done = os.path.join(data_dir, ".complete")
    if hvd.rank() == 0 and not os.path.exists(done):
        os.makedirs(data_dir, exist_ok=True)
        images, labels = synthetic_mnist(n=4096, seed=SEED)
        for s in range(NUM_SHARD_FILES):
            tmp = os.path.join(data_dir, f".tmp_shard_{s:03d}.npz")
            np.savez(tmp, images=images[s::NUM_SHARD_FILES],
                     labels=labels[s::NUM_SHARD_FILES])
            os.rename(tmp, os.path.join(data_dir, f"shard_{s:03d}.npz"))
        with open(done, "w") as f:
            f.write("ok")
    # Every rank (incl. 0) synchronizes on the marker through a
    # broadcast, so no rank globs a half-written directory.
    hvd.broadcast_object(True, root_rank=0, name="shards.ready")
    import time
    while not os.path.exists(done):  # pragma: no cover - NFS lag guard
        time.sleep(0.05)


def main():
    hvd.init()
    materialize_synthetic_shards(DATA_DIR)

    ds = ShardedFileDataset(DATA_DIR, hvd.rank(), hvd.size())
    print(f"[rank {hvd.rank()}] reading {len(ds.mine)}/{len(ds.files)} "
          f"shard files from {DATA_DIR}")

    model = MnistConvNet()
    rng = jax.random.PRNGKey(42)
    params = model.init({"params": rng}, jnp.ones((1, 28, 28, 1)),
                        train=False)["params"]
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt = hvd.DistributedOptimizer(optax.sgd(0.01 * hvd.size(),
                                             momentum=0.9))
    state = opt.init(params)

    @jax.jit
    def grads_fn(params, images, labels):
        def loss_fn(p):
            logits = model.apply({"params": p}, images, train=False)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
        return jax.value_and_grad(loss_fn)(params)

    step = 0
    for epoch in range(EPOCHS):
        for images, labels in ds.epoch_batches(epoch, BATCH):
            loss, grads = grads_fn(params, jnp.asarray(images),
                                   jnp.asarray(labels))
            updates, state = opt.update(grads, state, params)
            params = optax.apply_updates(params, updates)
            if step % 10 == 0 and hvd.rank() == 0:
                print(f"epoch {epoch} step {step}: loss {float(loss):.4f}")
            step += 1
            if step >= STEPS:
                break
        if step >= STEPS:
            break
    if hvd.rank() == 0:
        print(f"done: {step} steps, final loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
