#!/usr/bin/env python
"""MNIST with *eager* (out-of-jit) collectives — the TPU-native equivalent
of examples/tensorflow_mnist_eager.py (GradientTape + hvd.allreduce per
gradient, no graph).

Demonstrates the async handle API: gradients are enqueued as they are
produced and the engine fuses concurrently in-flight allreduces into one
XLA program (tensor fusion), then handles are synchronized before the
update — the reference's DistributedOptimizer hook pattern done by hand.
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import MnistConvNet

from _data import synthetic_mnist, shard_for_rank  # noqa: E402

BATCH = 64
STEPS = int(os.environ.get("STEPS", 30))


def main():
    hvd.init()
    images, labels = synthetic_mnist()
    images, labels = shard_for_rank((images, labels), hvd.rank(), hvd.size())

    model = MnistConvNet()
    rng = jax.random.PRNGKey(0)
    params = model.init({"params": rng}, jnp.ones((1, 28, 28, 1)),
                        train=False)["params"]
    params = hvd.broadcast_parameters(params, root_rank=0)

    opt = optax.adam(1e-3 * hvd.size())
    opt_state = opt.init(params)

    grad_fn = jax.jit(jax.value_and_grad(
        lambda p, x, y, r: optax.softmax_cross_entropy_with_integer_labels(
            model.apply({"params": p}, x, train=True, rngs={"dropout": r}),
            y).mean()))

    n = images.shape[0]
    batch = min(BATCH, n)
    for step in range(STEPS):
        i = (step * batch) % (n - batch + 1)
        x = jnp.asarray(images[i:i + batch])
        y = jnp.asarray(labels[i:i + batch])
        loss, grads = grad_fn(params, x, y, jax.random.fold_in(rng, step))

        # Eager per-gradient async allreduce: enqueue all, then sync —
        # concurrently in-flight requests get fused (tensor fusion).
        flat, treedef = jax.tree_util.tree_flatten(grads)
        handles = [hvd.allreduce_async(g, average=True,
                                       name=f"grad.{step}.{k}")
                   for k, g in enumerate(flat)]
        avg = [hvd.synchronize(h) for h in handles]
        grads = jax.tree_util.tree_unflatten(treedef, avg)

        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if step % 10 == 0 and hvd.rank() == 0:
            print(f"step {step:4d}  loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
