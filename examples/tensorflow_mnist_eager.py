#!/usr/bin/env python
"""TensorFlow eager MNIST — the TPU-native equivalent of
examples/tensorflow_mnist_eager.py: DistributedGradientTape averaging
gradients per step, broadcast after the first step (when variables
exist), rank-0 checkpointing.
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)

# tf.keras IS Keras 3 and honors KERAS_BACKEND; a stray
# "torch"/"jax" value from the environment would silently run
# this TF example on another backend and break GradientTape.
os.environ["KERAS_BACKEND"] = "tensorflow"

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd

from _data import synthetic_mnist, shard_for_rank  # noqa: E402

BATCH = 64
STEPS = int(os.environ.get("STEPS", 60))


def main():
    hvd.init()

    images, labels = synthetic_mnist()
    images, labels = shard_for_rank((images, labels),
                                    hvd.rank(), hvd.size())

    model = tf.keras.Sequential([
        tf.keras.layers.Input((28, 28, 1)),
        tf.keras.layers.Conv2D(16, 3, activation="relu"),
        tf.keras.layers.MaxPooling2D(2),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(10),
    ])
    opt = tf.keras.optimizers.Adam(1e-3 * hvd.size())
    loss_obj = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)

    n = images.shape[0]
    batch = min(BATCH, n)
    for step in range(STEPS):
        i = (step * batch) % (n - batch + 1)
        x = tf.constant(images[i:i + batch])
        y = tf.constant(labels[i:i + batch])
        # DistributedGradientTape allreduces in gradient() (reference
        # :78-90).
        with hvd.DistributedGradientTape() as tape:
            loss = loss_obj(y, model(x, training=True))
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))

        if step == 0:
            # Variables exist only after the first step in eager mode —
            # broadcast then (reference :92-98).
            hvd.broadcast_variables(model.variables, root_rank=0)
        if step % 20 == 0 and hvd.rank() == 0:
            print(f"step {step:4d}  loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
