#!/usr/bin/env python
"""Advanced Keras MNIST — the TPU-native equivalent of
examples/keras_mnist_advanced.py (127 LoC): LR warmup over the first
epochs, metric averaging across ranks, and epoch-scaled training.

Demonstrates the full callback suite:
  - BroadcastGlobalVariablesCallback: weight sync at train start
  - LearningRateWarmupCallback: gradual 1/N -> 1 ramp of the scaled LR
  - MetricAverageCallback: epoch metrics averaged over ranks
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)

os.environ.setdefault("KERAS_BACKEND", "torch")

import keras  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
import horovod_tpu.keras as hvd_keras  # noqa: E402
import horovod_tpu.keras.callbacks as hvd_callbacks  # noqa: E402

from _data import synthetic_mnist, shard_for_rank  # noqa: E402

EPOCHS = int(os.environ.get("EPOCHS", 4))
WARMUP_EPOCHS = 2


def main():
    hvd.init()

    images, labels = synthetic_mnist(n=8192)
    x_train, y_train = shard_for_rank((images, labels),
                                      hvd.rank(), hvd.size())

    model = keras.Sequential([
        keras.layers.Input((28, 28, 1)),
        keras.layers.Conv2D(32, (3, 3), activation="relu"),
        keras.layers.Conv2D(64, (3, 3), activation="relu"),
        keras.layers.MaxPooling2D((2, 2)),
        keras.layers.Dropout(0.25),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dropout(0.5),
        keras.layers.Dense(10, activation="softmax"),
    ])

    opt = hvd_keras.DistributedOptimizer(
        keras.optimizers.Adam(learning_rate=1e-3 * hvd.size()))
    model.compile(optimizer=opt,
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], jit_compile=False)

    callbacks = [
        hvd_callbacks.BroadcastGlobalVariablesCallback(0),
        hvd_callbacks.MetricAverageCallback(),
        # Scale-up warmup: LR ramps from lr/N to lr over WARMUP_EPOCHS
        # (keras_mnist_advanced.py + _keras/callbacks.py:149-168).
        hvd_callbacks.LearningRateWarmupCallback(
            warmup_epochs=WARMUP_EPOCHS, verbose=hvd.rank() == 0),
    ]

    model.fit(x_train, y_train, batch_size=128, epochs=EPOCHS,
              callbacks=callbacks, verbose=1 if hvd.rank() == 0 else 0)

    score = model.evaluate(x_train[:512], y_train[:512], verbose=0)
    if hvd.rank() == 0:
        print(f"loss {score[0]:.4f}  accuracy {score[1]:.4f}")


if __name__ == "__main__":
    main()
