#!/usr/bin/env python
"""PyTorch ResNet-50 ImageNet-style training through the torch shim — the
TPU-native equivalent of examples/pytorch_imagenet_resnet50.py (274 LoC):
gradient accumulation via backward_passes_per_step, warmup + staged LR,
fp16 gradient compression, rank-0 checkpointing, averaged metrics.

Synthetic data stands in for ImageNet (no egress).
"""

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:0] = [_HERE, os.path.dirname(_HERE)]  # _data + repo root (uninstalled runs)

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd

from _data import synthetic_imagenet  # noqa: E402


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--batches-per-allreduce", type=int, default=2,
                   help="gradient accumulation factor")
    p.add_argument("--base-lr", type=float, default=0.0125)
    p.add_argument("--warmup-epochs", type=float, default=1)
    p.add_argument("--fp16-allreduce", action="store_true")
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--checkpoint-format",
                   default="/tmp/hvd_tpu_pt_resnet/ckpt-{epoch}.pt")
    return p.parse_args()


def main():
    args = parse_args()
    hvd.init()
    torch.manual_seed(7)

    from _data import torch_image_model
    model, _model_name = torch_image_model("resnet50")

    # Accumulation multiplies the effective batch; scale LR accordingly
    # (reference :117-124).
    lr_scaler = args.batches_per_allreduce * hvd.size()
    opt = torch.optim.SGD(model.parameters(), lr=args.base_lr * lr_scaler,
                          momentum=0.9, weight_decay=5e-5)
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters(),
        compression=compression,
        backward_passes_per_step=args.batches_per_allreduce)

    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    imgs, lbls = synthetic_imagenet(args.batch_size * 8, args.image_size,
                                    100, seed=hvd.rank())
    x = torch.from_numpy(np.transpose(imgs, (0, 3, 1, 2)))
    y = torch.from_numpy(lbls.astype(np.int64))
    n = x.shape[0]

    steps_per_epoch = n // args.batch_size

    def adjust_lr(epoch, batch_idx):
        """Warmup from lr/scale to lr, then staged decay (reference
        :167-183)."""
        if epoch < args.warmup_epochs:
            ep = epoch + float(batch_idx + 1) / steps_per_epoch
            lr_adj = 1.0 / hvd.size() * (
                ep * (hvd.size() - 1) / args.warmup_epochs + 1)
        elif epoch < 30:
            lr_adj = 1.0
        elif epoch < 60:
            lr_adj = 1e-1
        elif epoch < 80:
            lr_adj = 1e-2
        else:
            lr_adj = 1e-3
        for g in opt.param_groups:
            g["lr"] = args.base_lr * lr_scaler * lr_adj

    for epoch in range(args.epochs):
        model.train()
        for bi in range(steps_per_epoch):
            adjust_lr(epoch, bi)
            opt.zero_grad()
            # Accumulate over sub-batches before the fused allreduce
            # fires (backward_passes_per_step).
            for k in range(args.batches_per_allreduce):
                i = ((bi * args.batches_per_allreduce + k)
                     * args.batch_size) % (n - args.batch_size)
                loss = F.cross_entropy(model(x[i:i + args.batch_size]),
                                       y[i:i + args.batch_size])
                loss = loss / args.batches_per_allreduce
                loss.backward()
            opt.step()
        if hvd.rank() == 0:
            os.makedirs(os.path.dirname(args.checkpoint_format),
                        exist_ok=True)
            torch.save({"model": model.state_dict(),
                        "optimizer": opt.state_dict()},
                       args.checkpoint_format.format(epoch=epoch))
            print(f"epoch {epoch}: last loss "
                  f"{float(loss) * args.batches_per_allreduce:.4f}")


if __name__ == "__main__":
    main()
